"""Extender scheduling logic: the ``sort`` and ``bind`` verbs.

Control flow mirrors the reference hot loop (SURVEY.md §3.2): per feasible
node, parse cluster state -> select best chip combo -> score; the scheduler
picks the max-score node and calls ``bind``, which re-runs the selector on
the winner, stamps the three-field assignment handshake onto the pod
(design.md:223-234: GROUP / ASSUME_TIME / ASSIGNED=false), and binds.

TPU-native departures from the reference, per SURVEY.md §5/§7:

- Scores are predicted all-reduce GB/s normalized to the domain ideal
  (direction bug fixed: higher == better).
- A pod's chips must live on its node (a pod runs on one host), so jobs
  larger than one host are *gangs*: pods sharing ``tpu.dev/gang-id`` with a
  ``tpu.dev/gang-size`` count.  Gang placement plans one replica per host
  over a host-grid torus, preferring a contiguous host box so the combined
  chip set is ICI-contiguous (BASELINE configs 3-5).  All-or-nothing is
  enforced at bind (the extender has no Filter verb by design,
  design.md:115-117): an infeasible gang binds nothing, and members that
  already hold assumptions expire together via the gang-aware TTL GC.
"""

from __future__ import annotations

import bisect
import functools
import math
import random
import threading
import time
from operator import itemgetter
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: informer is an optional dependency
    from tputopo.k8s.informer import Informer

from tputopo.k8s import objects as ko
from tputopo.k8s.fakeapi import Conflict, FakeApiServer, NotFound
from tputopo.k8s.retry import (ApiTimeout, ApiUnavailable, RetryPolicy,
                               bind_retry)
from tputopo.obs import NULL_TRACER, Tracer
from tputopo.extender.config import ExtenderConfig
from tputopo.extender.state import (ClusterState, PodAssignment, SliceDomain,
                                    _assume_time_of, _pod_assignment_of,
                                    full_sync, list_pods_nocopy)
from tputopo.topology.model import ChipTopology, Coord
from tputopo.topology.score import (_box_of, predict_allreduce_gbps,
                                    predict_multidomain_allreduce_gbps)
from tputopo.topology.slices import (Allocator, Placement, _boxes_within,
                                     enumerate_shapes, mask_bits_array)

# Gang metadata lives in labels (selectable) with annotation fallback.
LABEL_GANG_ID = "tpu.dev/gang-id"
LABEL_GANG_SIZE = "tpu.dev/gang-size"
# Opt-in: a gang that may split across ICI domains (TPU multislice — DP
# replicas sync gradients over DCN between slices).  Off by default: the
# contiguity guarantee is the framework's core promise.
LABEL_ALLOW_MULTISLICE = "tpu.dev/allow-multislice"

MAX_PRIORITY = 10  # kube-scheduler extender priority ceiling

#: The one max-score selection rule every sort consumer applies —
#: highest Score, host name as the deterministic tie-break (C-level key).
BEST_SCORE_KEY = itemgetter("Score", "Host")


@functools.lru_cache(maxsize=256)
def _host_grid(generation, grid_dims: tuple[int, ...],
               wrap: tuple[bool, ...]) -> ChipTopology:
    """The host-level torus a gang plans over.  Cached on value: building
    it fresh per plan call re-derived the grid's chips/neighbors/hosts
    tables every time (~0.8 s across one fleet-scale trace)."""
    return ChipTopology(generation, grid_dims, wrap)


class BindError(RuntimeError):
    """A bind verb failure.  ``reason`` is the structured failure class
    (``conflict`` / ``unavailable`` / ``timeout`` / ``gang_infeasible`` /
    ``wrong_node`` / ``not_found`` / ``already_bound`` / ``error``) — what
    the sim's retry-by-reason accounting and a caller deciding between
    re-queue and re-plan key on, instead of parsing the message.

    ``cause`` refines a ``conflict`` under replicated deployments
    (``ExtenderConfig.shared_writers``): ``lost_race`` (a genuinely
    concurrent peer claim won the arbitration), ``stale_cache`` (the
    losing plan was built from a view that provably predated the winning
    claim — a fresh sync would have avoided the collision), or
    ``ambiguous_timeout`` (the post-conflict re-read could not determine
    the winner; the TTL GC remains the backstop).  None outside
    shared-writer mode — single-scheduler conflicts keep their historical
    shape."""

    def __init__(self, msg: str, reason: str = "error",
                 cause: str | None = None) -> None:
        super().__init__(msg)
        self.reason = reason
        self.cause = cause


def quantile(sorted_xs, q: float):
    """Ceil-based empirical quantile ``xs[min(n-1, ceil(n*q)-1)]`` over an
    already-sorted sequence — the one rank convention every exporter in
    this repo uses (Metrics here, bench.py's pct(), the sim report), so a
    p95 compared across surfaces is the same statistic.  Unlike the old
    ``int(n*q)-1`` rank it is not biased low at small n: p95 of 10
    samples is the max (rank 10), not the 9th value (p90)."""
    n = len(sorted_xs)
    return sorted_xs[min(n - 1, max(0, math.ceil(n * q) - 1))]


@dataclass
class Metrics:
    counters: dict[str, int] = field(default_factory=dict)
    # Per-verb latency samples, bounded to a recent window: a long-lived
    # extender observes millions of verbs, and the former unbounded lists
    # grew without limit (the "ever-growing lists" note).  Quantiles are
    # computed over the retained window with the same ceil-rank convention,
    # so exported p50/p95 become rolling-window statistics.  Plain lists,
    # not deques: sorted()/list() of a list snapshot atomically under the
    # GIL, so a /metrics scrape never races a verb thread's append (a
    # deque iterator raises RuntimeError on any concurrent mutation).
    latencies_ms: dict[str, list[float]] = field(default_factory=dict)

    # Prometheus-grade cumulative histograms per verb, alongside the
    # windowed quantile gauges above: a scraper computing rates/apdex
    # needs monotone ``_bucket``/``_sum``/``_count`` series over the
    # process lifetime, which a rolling window cannot provide.  Buckets
    # are fixed (never per-process adaptive — two extenders must export
    # comparable series); bounds chosen for a verb whose p50 sits in the
    # sub-ms range and whose SLO tail is tens of ms.
    hist_counts: dict[str, list[int]] = field(default_factory=dict)
    hist_sum_ms: dict[str, float] = field(default_factory=dict)

    #: Upper bounds (ms) of the histogram buckets; one implicit +Inf
    #: bucket follows.  Fixed by contract — see hist_counts.
    HIST_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                       100.0, 250.0, 1000.0)

    #: Samples retained per series.  4096 covers minutes of peak verb
    #: traffic — far more than any quantile needs to be stable — while
    #: bounding memory at a few tens of KB per series.
    LATENCY_WINDOW = 4096

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def observe_ms(self, name: str, ms: float) -> None:
        xs = self.latencies_ms.setdefault(name, [])
        xs.append(ms)
        if len(xs) > self.LATENCY_WINDOW:
            del xs[: len(xs) - self.LATENCY_WINDOW]
        hist = self.hist_counts.get(name)
        if hist is None:
            hist = self.hist_counts[name] = \
                [0] * (len(self.HIST_BUCKETS_MS) + 1)
        # bisect_left: the first bucket whose bound is >= the sample —
        # Prometheus ``le`` semantics; past the last bound lands in +Inf.
        hist[bisect.bisect_left(self.HIST_BUCKETS_MS, ms)] += 1
        self.hist_sum_ms[name] = self.hist_sum_ms.get(name, 0.0) + ms

    def histogram(self, name: str) -> tuple[list[tuple[float, int]], float, int] | None:
        """Cumulative (le_bound, count) pairs (+Inf last), sum and count —
        the Prometheus exposition shape, computed from the per-bucket
        increments under the GIL's list-snapshot atomicity."""
        hist = self.hist_counts.get(name)
        if hist is None:
            return None
        hist = list(hist)  # atomic snapshot vs. concurrent observe_ms
        out, cum = [], 0
        for bound, n in zip(self.HIST_BUCKETS_MS, hist):
            cum += n
            out.append((bound, cum))
        cum += hist[-1]
        out.append((math.inf, cum))
        return out, self.hist_sum_ms.get(name, 0.0), cum

    def p50_ms(self, name: str) -> float | None:
        return (self.quantiles_ms(name, (0.5,)) or (None,))[0]

    def p95_ms(self, name: str) -> float | None:
        return (self.quantiles_ms(name, (0.95,)) or (None,))[0]

    def quantiles_ms(self, name: str,
                     qs: tuple[float, ...]) -> tuple[float, ...] | None:
        """Several quantiles from ONE sort (scrapes ask for p50+p95 on
        ever-growing lists), via :func:`quantile` — the ceil-based rank
        shared with bench.py's pct(), so the exported p95 and the
        benched/gated p95 agree on identical data."""
        xs = sorted(self.latencies_ms.get(name, []))
        if not xs:
            return None
        return tuple(quantile(xs, q) for q in qs)


def _pod_meta_get(md: dict, key: str, default=None):
    """Labels-over-annotations metadata lookup WITHOUT materializing the
    merged dict — by construction exactly
    ``{**md["annotations"], **md["labels"]}.get(key, default)``, including
    a label explicitly present with a None value shadowing an annotation
    (presence, not truthiness, decides the shadow).  The
    ``BIND_ANN_TEMPLATE`` fast path for the per-pod-per-verb gang
    metadata probes, which at XL scale built millions of one-shot merge
    dicts."""
    labels = md.get("labels")
    if labels is not None and key in labels:
        return labels[key]
    anns = md.get("annotations")
    if anns is not None and key in anns:
        return anns[key]
    return default


def _wanted_generation(pod: dict) -> str | None:
    """Pod-requested TPU generation (label or annotation tpu.dev/generation)
    — the Gaia heterogeneous-quota rule (PDF §III.A): one workload never
    receives mixed accelerator types.  Single-pod requests can't mix by
    construction (one node = one generation); this gate lets a pod *pin* a
    generation so it never lands on the wrong pool at all."""
    md = pod.get("metadata", {})
    if ExtenderScheduler.BIND_ANN_TEMPLATE:
        return _pod_meta_get(md, ko.ANN_GENERATION_LABEL)
    meta = {**md.get("annotations", {}), **md.get("labels", {})}
    return meta.get(ko.ANN_GENERATION_LABEL)


def bound_as_planned(pod: dict, node_name: str, group: str) -> bool:
    """True when ``pod`` is bound to ``node_name`` carrying exactly the
    chip-group annotation ``group`` — THE predicate for "this Conflict is
    the echo of my own timed-out-but-applied bind".  Shared by the bind
    verb's reconciliation and the sim baseline policy, so the rule can
    never drift between them."""
    return (pod.get("spec", {}).get("nodeName") == node_name
            and pod.get("metadata", {}).get("annotations", {})
                   .get(ko.ANN_GROUP) == group)


def _gang_of(pod: dict) -> tuple[str, str, int] | None:
    """(namespace, gang_id, size) — gang identity is namespace-scoped so
    same-named gangs in different namespaces never merge."""
    md = pod.get("metadata", {})
    if ExtenderScheduler.BIND_ANN_TEMPLATE:
        gid = _pod_meta_get(md, LABEL_GANG_ID)
        raw_size = _pod_meta_get(md, LABEL_GANG_SIZE, "0")
    else:
        meta = {**md.get("annotations", {}), **md.get("labels", {})}
        gid = meta.get(LABEL_GANG_ID)
        raw_size = meta.get(LABEL_GANG_SIZE, "0")
    if not gid:
        return None
    try:
        size = int(raw_size)
    except ValueError:
        size = 0
    if size < 1:
        raise ValueError(f"gang {gid!r} needs a positive {LABEL_GANG_SIZE} label")
    return md.get("namespace", "default"), gid, size


# Canonical lock order (outermost first) — enforced whole-program by the
# lock-order lint rule: acquiring a lock to the LEFT of one already held
# is a finding, and any cycle in the derived acquisition graph is a
# potential deadlock.  The bind verb is the outermost critical section;
# it publishes through the cache pair, writes through to the informer
# mirror, and commits via the API server's own lock.
# lock-order: ExtenderScheduler._bind_lock > ExtenderScheduler._cache_lock > Informer._lock > FakeApiServer._lock


class ExtenderScheduler:
    def __init__(self, api_server: FakeApiServer,
                 config: ExtenderConfig | None = None,
                 clock=time.time, informer: "Informer | None" = None,
                 tracer=None, retry: RetryPolicy | None = None,
                 retry_rng=None, wall=time.perf_counter) -> None:
        self.api = api_server
        self.config = config or ExtenderConfig()
        self.clock = clock
        # Claim-arbitration listing (shared_writers mode): the indexed
        # assignment-carrying-pods read where the API surface provides one
        # (FakeApiServer/KubeApiClient.list_assignments — O(assignments)),
        # with the whole-store shim as the constructor-bound fallback so
        # the bind verb's own call graph never contains a full-store scan
        # (the same binding trick AssumptionGC uses).
        self._list_claims_raw = getattr(api_server, "list_assignments",
                                        None) or functools.partial(
                                            list_pods_nocopy, api_server)
        # Verb-latency telemetry rides an injectable wall hook (the
        # clock=time.time default-arg idiom, obs.Tracer style): the
        # values feed observe_ms/histograms only — never a decision — and
        # the indirection keeps the transitive wall-clock effect out of
        # the sim's reach (clock-flow lint rule), pinnable in tests.
        self._wall = wall
        # Shared retry discipline (tputopo.k8s.retry) for the API calls the
        # verbs make: transient 5xx/timeouts back off and retry instead of
        # surfacing as hard verb failures.  Sleep rides the clock when it
        # carries one (the sim's VirtualClock advances virtual time —
        # deterministic backoff); ``retry_rng`` seeds the jitter — the
        # sim pins one, and the default is per-instance entropy so N
        # deployed extenders never retry a flapping apiserver in
        # lockstep (the whole point of jitter).
        self.retry = retry if retry is not None else RetryPolicy()
        self._retry_rng = retry_rng if retry_rng is not None \
            else random.Random()
        # Flight recorder (tputopo.obs): sort/bind open a trace with
        # nested phase spans and attach a per-decision explain record.
        # An explicit ``tracer`` wins (the sim injects its virtual-clock
        # tracer so explain timestamps are deterministic); otherwise the
        # config knob decides, and disabled means the shared NULL_TRACER
        # — a no-op object the hot path pays attribute lookups for, not
        # allocations.
        if tracer is not None:
            self.tracer = tracer
        elif self.config.trace_enabled:
            self.tracer = Tracer(capacity=self.config.trace_capacity,
                                 clock=clock)
        else:
            self.tracer = NULL_TRACER
        # Optional list+watch cache (k8s/informer.py).  When present and
        # synced, `sort` AND `bind` build their state from the cache — zero
        # LISTs against the API server in steady state (the nodeCacheCapable
        # posture, design.md:102).  Bind's writes stay authoritative (API
        # CAS) and write-through to the mirror, publishing a delta-applied
        # derived state so neither verb pays an O(pods) re-sync per call.
        self.informer = informer
        self.metrics = Metrics()
        self._retry_call = bind_retry(self.retry, clock, self._retry_rng,
                                      inc=self.metrics.inc)
        self.decisions: list[dict] = []  # recent decision records (observability)
        # The published derived-state pair: reads are lock-free by design
        # (token-first read order + idempotent re-folds tolerate torn READ
        # pairings — see _delta_from_informer), writes serialize under
        # _cache_lock so an old state can never pair with a newer token.
        self._cached_state: ClusterState | None = None  # guarded-by: _cache_lock (writes)
        self._cached_at: float = 0.0  # guarded-by: _cache_lock (writes)
        # guarded-by: _cache_lock (writes)
        self._cached_informer_version: tuple[str, ...] | None = None
        # Serializes WRITES of the (state, token) pair: sorts are lock-free
        # readers, but two concurrent publishers (sort folds, binds) could
        # otherwise interleave the two attribute writes and pair an old
        # state with a newer token — which the version check would then
        # wrongly serve as current.  Reads stay unlocked: the token-first
        # read order plus idempotent re-folding tolerates every torn READ
        # pairing (see _delta_from_informer).
        self._cache_lock = threading.Lock()
        # bind's sync -> select -> patch sequence is not atomic; the HTTP
        # server is threaded, so serialize binds process-wide.  (The
        # kube-scheduler also serializes binds per cycle — this is defense
        # in depth for direct API users and a future multi-verb world.)
        self._bind_lock = threading.Lock()
        # Binds whose post-write mirror write-through FAILED (read-back
        # error): until each is repaired, the mirror may lack a committed
        # placement, so binds must fall back to the authoritative API sync
        # — otherwise a bind planned from the stale mirror could double-
        # book those chips (the per-pod CAS cannot catch cross-pod
        # overlap).  Entries are (namespace, pod_name).
        self._unmirrored_binds: set[tuple[str, str]] = set()  # guarded-by: _bind_lock
        # Cross-state gang plan carry: the per-state memo above dies with
        # each derived state, and bind re-syncs per member — so an N-member
        # gang used to re-plan from scratch N times (VERDICT r2 #5).  A
        # successful plan is kept here keyed by gang identity and REVALIDATED
        # against the authoritative state before reuse (planned chips still
        # free, bound members consistent) — plan stability across a gang's
        # bind sequence is exactly the semantics binding wants anyway.
        # Guarded: sorts run concurrently on the threaded HTTP server, and
        # the LRU pop-then-insert refresh is a non-atomic sequence the
        # lockset rule flagged — _cache_lock serializes it (bind already
        # nests _bind_lock > _cache_lock, so the order holds).
        self._gang_plan_cache: dict[tuple[str, str], dict] = {}  # guarded-by: _cache_lock
        # Vectorized gang screen (VECTOR_GANG_PLAN): per-domain bit->node
        # row layouts, keyed on the domain's node-mask table IDENTITY
        # (those dicts are immutable and shared across copy-on-write
        # states, so one layout serves every folded/delta state until a
        # full rebuild replaces the table).  The keyed object is held in
        # the value so a recycled id() can never alias a dead entry.
        self._vector_rows_cache: dict[int, tuple] = {}  # guarded-by: _cache_lock
        # Mask-native gang probe (MASK_GANG_PROBE): per-(domain, k) box
        # candidate vocabularies, keyed like the row layouts above on the
        # node-mask table identity (held in the value against id() reuse).
        self._mask_probe_cache: dict[tuple, tuple] = {}  # guarded-by: _cache_lock
        # Hoisted invariant annotation-dict parts (BIND_ANN_TEMPLATE):
        # config.replica_id is fixed at construction, so the assume-claim
        # and release-wipe payloads vary only in their per-placement keys
        # — dict(template)+patch replaces rebuilding each literal per
        # member per attempt.  Never mutated after construction.
        self._bind_ann_tmpl: dict = {ko.ANN_ASSIGNED: "false"}
        self._wipe_ann_tmpl: dict = {
            ko.ANN_GROUP: None, ko.ANN_ASSUME_TIME: None,
            ko.ANN_ASSIGNED: None, ko.ANN_PREDICTED_GBPS: None}
        if self.config.replica_id:
            self._bind_ann_tmpl[ko.ANN_BOUND_BY] = self.config.replica_id
            self._wipe_ann_tmpl[ko.ANN_BOUND_BY] = None

    _GANG_PLAN_CACHE_MAX = 512

    #: Kill switch for the incremental score index (leg 2 of the fleet
    #: hot-path pass): the per-state ``{k: {node: score}}`` index read by
    #: the sort loop and maintained by the SAME engine events the state
    #: folds (only nodes of occupancy-changed domains re-score).  False
    #: restores the historical flat ``(k, node)`` score memo plus its
    #: per-fold filter-copy carry, byte-for-byte — hit counts
    #: (``score_memo_hits``) and explain ``memo_hit`` flags are identical
    #: under both shapes; only wall time moves.
    SCORE_INDEX = True

    #: Kill switch for the vectorized gang-composition screen (the
    #: saturation-wake pass): per-node free-chip counts for EVERY domain
    #: come from ONE numpy unpackbits+bincount batch over the
    #: concatenated free masks (memoized per state instance), and gang
    #: planning consults them as a sound NECESSARY condition — a domain
    #: whose >=k-free host count (or free volume) cannot cover the
    #: remaining replicas is skipped without building its per-host
    #: candidate map, and the multislice search's per-domain
    #: ``max_feasible`` probe starts at the screened bound instead of
    #: the host count.  Screening can only over-admit (delisted nodes'
    #: chips are counted), never reject a feasible domain, so plans,
    #: scores, binds, and every report byte are identical under both
    #: settings — only wall time moves.  False restores the historical
    #: probe-every-domain loop byte-for-byte.
    VECTOR_GANG_PLAN = True

    #: Kill switch for the exclude-keyed capacity memo (XL hot-path
    #: pass): ``_vector_cap`` answers are cached per state instance as
    #: ``{(k, frozenset(exclude)): {slice_id: cap}}``.  The per-k base
    #: caps were already memoized; what remained per call — and at 4096
    #: nodes ran ~14M times — was the excluded-host subtraction loop.
    #: Coherence rides the counts batch's existing staleness protocol:
    #: ``_vector_cap`` reads ``_vector_counts`` FIRST, whose patch step
    #: pops every staled domain from this memo before any hit can be
    #: served, and the wholesale layout-mismatch drop takes the memo
    #: with it.  A hit returns the identical int the loop would have
    #: recomputed, so plans and report bytes are unchanged; False
    #: restores the per-call subtraction loop byte-for-byte.
    VECTOR_CAP_MEMO = True

    #: Kill switch for dirty-set fold bookkeeping (XL hot-path pass):
    #: ``ClusterState`` records the slice_ids whose occupancy an
    #: in-place fold actually moved (``_dirty_sids``, maintained at the
    #: same mark/release sites the allocators mutate), and single-owner
    #: memo eviction consumes that set instead of snapshotting every
    #: domain's used_mask before the fold and re-comparing after — the
    #: two O(domains) passes per fold/bind that dominated XL fold wall.
    #: The dirty set can only OVER-approximate the mask-compare result
    #: (a release and a same-chips re-mark inside one fold batch cancel
    #: in the mask but still dirty the domain), so eviction stays sound
    #: and deterministic; gang-candidate eviction additionally walks a
    #: per-domain key index instead of scanning the whole memo.  False
    #: restores the snapshot-and-compare path byte-for-byte.
    DIRTY_FOLD = True

    #: Kill switch for bind-leg annotation templating (XL hot-path
    #: pass): the per-member assignment-annotation dicts, the gang
    #: release/claim wipe dicts, and the metadata lookups that backed
    #: them are built from hoisted invariant templates with only the
    #: varying keys patched per member, and gang metadata reads probe
    #: labels-then-annotations directly instead of materializing a
    #: merged ``{**annotations, **labels}`` dict per pod per verb.
    #: Every produced dict is equal by construction (labels shadow
    #: annotations exactly as the merge did, including explicit None
    #: values), so patch payloads and report bytes are identical under
    #: both settings; False restores the per-member literal dicts.
    BIND_ANN_TEMPLATE = True

    #: Kill switch for mask-native gang composition probes (XL hot-path
    #: pass): ``_plan_gang``'s per-host candidate search — for every
    #: host with >= k free chips, the best k-chip box inside the node —
    #: is answered from a precomputed per-(domain, k) candidate
    #: vocabulary (every box of every k-volume shape within each node's
    #: chip mask, scored and tie-ranked exactly as ``Allocator.find``
    #: orders them) with one numpy feasibility/fragmentation pass over
    #: all hosts' candidates, instead of a Python shape x origin walk
    #: per host.  Hosts whose free set defeats every vocabulary box
    #: (fragmented remainder needing the connected-blob fallback) fall
    #: back to the exact ``Allocator.find`` walk, counted
    #: (``gang_mask_probe_fallbacks``), so the candidate map — and
    #: every plan, bind, and report byte derived from it — is identical
    #: under both settings.  k == 1 probes (no box vocabulary) always
    #: take the exact walk.  False restores the per-host walk wholesale.
    MASK_GANG_PROBE = True

    @property
    def _single_owner(self) -> bool:
        """True when this scheduler provably holds the ONLY reference to
        its cached derived state AND is the sole writer of assignments:
        informer-less ``bind_from_cache`` mode (the sim engine's
        single-threaded single-writer deployment).  Only then may folds
        mutate in place — the threaded/informer paths publish states to
        lock-free concurrent readers and must keep the copy-on-write
        discipline, and ``shared_writers`` (replicated control plane)
        voids the sole-writer premise outright: a racing peer's commits
        make the in-place fold's invalidation contract unsatisfiable, so
        shared-writer state maintenance downgrades to COW-or-drop
        (tputopo.extender.replicas asserts this at construction)."""
        return (self.informer is None and self.config.bind_from_cache
                and not self.config.shared_writers)

    # Even with an unchanged informer mirror, a derived state cannot be
    # reused forever: assumption-TTL expiry is judged by the clock at sync
    # time, not by watch events.  5 s keeps worst-case expiry staleness far
    # under the 60 s assume TTL while still absorbing sort bursts.
    _INFORMER_STATE_MAX_AGE_S = 5.0

    def invalidate_cached_state(self) -> None:
        """Drop the cached derived state.  The public invalidation hook a
        ``bind_from_cache`` deployment MUST call after any out-of-band
        cluster mutation (pod create/delete, node churn, annotation wipes
        by an external GC) — the config's "sole writer" rule is only
        satisfiable through this method or :meth:`apply_events` (the sim's
        engine is the model consumer)."""
        with self._cache_lock:
            self._cached_state = None

    def apply_events(self, events) -> None:
        """Fold out-of-band cluster mutations the caller just made into the
        cached derived state copy-on-write (``(kind, event_type, object)``
        triples, informer vocabulary) instead of dropping it — the delta
        form of :meth:`invalidate_cached_state` for ``bind_from_cache``
        single-writer deployments.  Un-appliable events (node churn,
        overlapping claims) or ``state_delta=False`` degrade to a plain
        drop: the next verb re-syncs, never serves a stale view."""
        state = self._cached_state
        if state is None:
            return
        if not self.config.state_delta or \
                self._cached_informer_version is not None:
            # Informer-coherent states advance only through the mirror's
            # version token (the _state delta path) — an out-of-band fold
            # here would fork them from the token; drop instead.
            with self._cache_lock:
                self._cached_state = None
            return
        if not events:
            return  # nothing changed; the cached state is already exact
        reasons: list[str] = []
        if self._single_owner:
            # Single-owner fast path: fold by mutating the state we own
            # (ClusterState.fold_inplace — its FOLD_INPLACE kill switch
            # restores the COW clone byte-for-byte) and evict only the
            # memo entries the fold's occupancy changes invalidate,
            # instead of filter-copying every memo dict per fold.
            # DIRTY_FOLD skips the every-domain mask snapshot too: the
            # fold records the domains it moves (_dirty_sids), and
            # eviction consumes that set.
            use_dirty = self.DIRTY_FOLD and ClusterState.FOLD_INPLACE
            if use_dirty:
                pre_masks = None
                state._dirty_sids.clear()
            else:
                pre_masks = ({sid: dom.allocator.used_mask
                              for sid, dom in state.domains.items()}
                             if ClusterState.FOLD_INPLACE else None)
            new_state = state.fold_inplace(events, reasons)
        else:
            use_dirty = False
            new_state = state.with_events(events, reasons)
        if new_state is None:
            self._count_delta_fallback(reasons)
            with self._cache_lock:
                self._cached_state = None
        else:
            self.metrics.inc("state_delta_applied")
            if new_state is state:
                self._evict_state_memos(
                    state, pre_masks,
                    dirty=state._dirty_sids if use_dirty else None)
            else:
                new_state = self._carry_state_memos(state, new_state)
            with self._cache_lock:
                if self._cached_state is state:
                    self._cached_state = new_state
                else:  # replaced/invalidated meanwhile — stay conservative
                    self._cached_state = None

    def _carry_state_memos(self, old: ClusterState,
                           new: ClusterState) -> ClusterState:
        """Carry occupancy-pure memos (node scores, gang candidate maps)
        from a replaced derived state onto its delta successor, per domain
        whose occupancy mask did not move.  A node's score and a domain's
        per-host candidate map are pure functions of (domain occupancy, k)
        — folding an event that only touched OTHER domains (or none, e.g.
        a Pending pod ADDED) cannot invalidate them, and rescoring a
        256-node fleet per fold was the sort tail's dominant cost."""
        changed = {sid for sid, dom in old.domains.items()
                   if new.domains[sid].allocator.used_mask
                   != dom.allocator.used_mask}
        memo = getattr(old, "_score_memo", None)
        if memo:
            if changed:
                # Filter by a precomputed changed-NODE set: a fold never
                # changes the node->domain map (node churn forces a full
                # rebuild, which carries nothing), so one set membership
                # per key replaces the two-method domain lookup that was
                # the fold tail's top cost on thousand-node fleets.
                # list(items()) first: a concurrent lock-free sort may
                # still be inserting into the OLD state's memo, and the
                # C-level list snapshot is atomic where a comprehension
                # over a growing dict is not.
                changed_nodes = {n for sid in changed
                                 for n in new.domains[sid].host_by_node}
                kept = {key: v for key, v in list(memo.items())
                        if key[1] not in changed_nodes}
            else:
                kept = dict(memo)
            if kept:
                new._score_memo = kept
                self.metrics.inc("score_memo_carried", len(kept))
        sidx = getattr(old, "_score_index", None)
        if sidx:
            # The incremental score index (SCORE_INDEX shape), carried
            # across a COW replacement with the same changed-domain
            # filter as the flat memo above — hit behavior is identical,
            # only the layout differs (the in-place eviction path is
            # where the index pays off; see _evict_state_memos).  Same
            # atomic-snapshot rule as the memo: concurrent sorts insert
            # into the old state's buckets while this fold carries them.
            if changed:
                changed_nodes = {n for sid in changed
                                 for n in new.domains[sid].host_by_node}
                kept_idx = {k: {n: v for n, v in list(kd.items())
                                if n not in changed_nodes}
                            for k, kd in list(sidx.items())}
            else:
                kept_idx = {k: dict(kd) for k, kd in list(sidx.items())}
            new._score_index = kept_idx
        cand = getattr(old, "_gang_cand_memo", None)
        if cand:
            kept = {key: v for key, v in cand.items()
                    if key[0] not in changed}
            if kept:
                new._gang_cand_memo = kept
                # Rebuild the per-domain key index (DIRTY_FOLD eviction)
                # from exactly the carried keys — the old state's index
                # names keys this copy never held.
                by_dom: dict[str, set] = {}
                for key in kept:
                    by_dom.setdefault(key[0], set()).add(key)
                new._gang_cand_by_dom = by_dom
        return new

    def _evict_state_memos(self, state: ClusterState,
                           pre_masks: dict[str, int] | None,
                           dirty: set[str] | None = None) -> None:
        """The in-place twin of :meth:`_carry_state_memos`: after a
        single-owner fold mutated ``state`` directly, evict exactly the
        memo entries the COW path would have dropped — nodes of domains
        whose occupancy mask moved since ``pre_masks`` was snapshotted —
        in O(changed domains) instead of filter-copying every memo dict.
        Under DIRTY_FOLD the caller passes ``dirty`` instead: the
        slice_ids the fold itself recorded at its mark/release sites
        (``ClusterState._dirty_sids``), sparing both the pre-fold
        snapshot and the every-domain compare; the set can only
        over-approximate the compare (still sound — eviction of a
        still-valid entry merely recomputes it).  The gang
        context/member-list memos are dropped wholesale: the COW clone
        never carried them (member listings can change on any event,
        occupancy-moving or not), and in-place parity requires the
        same."""
        for attr in ("_gang_ctx_memo", "_gang_members_memo"):
            if getattr(state, attr, None) is not None:
                delattr(state, attr)
        if dirty is not None:
            self.metrics.inc("state_dirty_folds")
            changed = {sid for sid in dirty if sid in state.domains}
        else:
            changed = {sid for sid, dom in state.domains.items()
                       if dom.allocator.used_mask != pre_masks.get(sid)}
        if not changed:
            return
        # The vectorized gang screen's count batch is a pure function of
        # fleet occupancy, but a fold only moves the CHANGED domains'
        # rows — so the fold merely QUEUES those domain ids; the next
        # gang plan that actually reads the batch patches exactly the
        # stale windows (see _vector_counts).  Both eager alternatives
        # lost: dropping the cache wholesale made the batch planner
        # rebuild the full-fleet batch once per probe, and patching
        # here, per fold, paid the numpy round-trip for fold bursts no
        # plan ever read.
        vc = getattr(state, "_vector_counts_cache", None)
        if vc is not None:
            stale = getattr(state, "_vector_stale", None)
            if stale is None:
                stale = state._vector_stale = set()
            stale.update(changed)
        sidx = getattr(state, "_score_index", None)
        if sidx:
            # The batch planner's fill bookkeeping (batch_scores) rides
            # the same eviction: every popped node lands in the per-k
            # dirty set so the next batch scoring pass rescored exactly
            # these — the bookkeeping exists only on states a batch
            # plan has scored, so non-batch runs never touch it.
            bfill = getattr(state, "_batch_filled", None)
            changed_hosts = [n for sid in changed
                             for n in state.domains[sid].host_by_node]
            for k, kd in sidx.items():
                for n in changed_hosts:
                    kd.pop(n, None)
                if bfill is not None:
                    d = bfill.get(k)
                    if d is not None:
                        d.update(changed_hosts)
        memo = getattr(state, "_score_memo", None)
        if memo:
            changed_nodes = {n for sid in changed
                             for n in state.domains[sid].host_by_node}
            for key in [key for key in memo if key[1] in changed_nodes]:
                del memo[key]
        cand = getattr(state, "_gang_cand_memo", None)
        if cand:
            by_dom = getattr(state, "_gang_cand_by_dom", None)
            if self.DIRTY_FOLD and by_dom is not None:
                # O(evicted) via the per-domain key index instead of
                # scanning every memo key — the comprehension below
                # scales with total memoized (domain, k, exclude) keys,
                # which at 4096 nodes dwarfs the handful a fold moves.
                for sid in changed:
                    for key in by_dom.pop(sid, ()):
                        cand.pop(key, None)
            else:
                for key in [key for key in cand if key[0] in changed]:
                    del cand[key]
                if by_dom is not None:
                    for sid in changed:
                        by_dom.pop(sid, None)

    def _count_delta_fallback(self, reasons: list[str] | str) -> None:
        """One forced full rebuild, attributed: the flat
        ``state_delta_fallbacks`` counter stays (dashboards key on it)
        and a per-reason sibling (``state_delta_fallback_node_churn`` /
        ``_journal_gap`` / ``_conflict`` / ``_overlap`` / ``_other``)
        says WHY the delta path bailed — the difference between tuning
        the journal depth and chasing phantom node churn."""
        reason = reasons if isinstance(reasons, str) else \
            (reasons[0] if reasons else "other")
        self.metrics.inc("state_delta_fallbacks")
        self.metrics.inc(f"state_delta_fallback_{reason}")

    def _delta_from_informer(self, reader) -> ClusterState | None:
        """Advance the cached informer-coherent state to the mirror's
        current content by folding the watch events in between (the
        journal), or None when only a full rebuild is exact (no cached
        state, journal gap/relist, un-appliable event, expiry-judgement
        age bound exceeded)."""
        # Snapshot, TOKEN FIRST: sorts are lock-free by design, so a
        # concurrent bind may publish a newer (state, token) pair between
        # these two reads.  Reading the token before the state means a torn
        # read can only pair an OLD token with a NEW state — folding the
        # journal tail then re-applies events the state already reflects,
        # which the event folding is idempotent for (upsert of an identical
        # assignment updates in place; delete/wipe of an absent record is a
        # no-op).  The opposite pairing (new token, old state) would
        # persist a state MISSING a bind under a token that claims it is
        # current — that is the order this read forbids.
        token = self._cached_informer_version
        state = self._cached_state
        if (not self.config.state_delta
                or state is None or token is None
                or self.clock() - self._cached_at
                    >= self._INFORMER_STATE_MAX_AGE_S):
            return None
        fetch = getattr(reader, "events_since", None)
        if fetch is None:
            return None
        got = fetch(token)
        if got is None:
            # Token fell off the bounded journal or a relist landed in
            # the span — the informer cannot reconstruct the delta.
            self._count_delta_fallback("journal_gap")
            return None
        events, new_token = got
        if not events:
            return state  # token already current (raced version read)
        reasons: list[str] = []
        new_state = state.with_events(events, reasons)
        if new_state is None:
            self._count_delta_fallback(reasons)
            return None
        self.metrics.inc("state_delta_applied")
        new_state = self._carry_state_memos(state, new_state)
        with self._cache_lock:
            # Publish only if no concurrent publisher advanced the pair
            # past what we folded from; either way new_state is coherent
            # at new_token and serves THIS verb.
            if (self._cached_state is state
                    and self._cached_informer_version == token):
                self._cached_state = new_state
                self._cached_informer_version = new_token
                # _cached_at deliberately NOT refreshed: it stamps when
                # occupancy was last judged against the clock (assume-TTL
                # expiry happens only at sync), and the age bound above
                # must keep holding under sustained event traffic.
        return new_state

    def _state(self, allow_cache: bool = False, reader=None,
               span=None) -> ClusterState:
        # ``span``: the calling verb's "state" phase span (tracing) — it
        # records HOW the state was obtained (cache hit / journal fold /
        # full rebuild) and nests a child span around the O(cluster) sync
        # so rebuild cost is attributable per trace.  None (the default
        # and every untraced caller) costs nothing.
        if span is None:
            span = NULL_TRACER.start("state")  # shared no-op span
        if allow_cache and reader is not None:
            # Cache-backed sync: ClusterState reads the informer's local
            # mirror through the same list() surface — no API-server LISTs.
            # Rebuild only when the mirror changed (rv token) or the derived
            # state aged past the expiry-staleness bound; a sort burst
            # otherwise reuses one build, and a burst under churn folds the
            # mirror's event deltas instead of rebuilding per tick.
            version = reader.version()
            if (self._cached_state is not None
                    and self._cached_informer_version == version
                    and self.clock() - self._cached_at
                        < self._INFORMER_STATE_MAX_AGE_S):
                self.metrics.inc("state_cache_hits")
                span.count("cache_hit")
                return self._cached_state
            state = self._delta_from_informer(reader)
            if state is not None:
                span.count("journal_fold")
                return state
            self.metrics.inc("state_from_informer")
            self.metrics.inc("state_full_rebuilds")
            span.count("full_rebuild")
            with span.child("sync"):
                # The counted cache-miss fallback (state_full_rebuilds);
                # the delta/journal-fold paths above are the steady state.
                state = full_sync(
                    reader,
                    cost_for_generation=self.config.cost_model,
                    assume_ttl_s=self.config.assume_ttl_s,
                    clock=self.clock,
                )
            with self._cache_lock:
                self._cached_state = state
                self._cached_at = self.clock()
                # The PRE-build token: if the mirror advanced mid-build,
                # the next verb folds (or re-folds — the event application
                # is idempotent for upserts the state already reflects) the
                # tail rather than ever serving a view older than its token.
                self._cached_informer_version = version
            return state
        ttl = self.config.state_cache_s
        if (allow_cache and ttl > 0 and self._cached_state is not None
                and self.clock() - self._cached_at < ttl):
            self.metrics.inc("state_cache_hits")
            span.count("cache_hit")
            return self._cached_state
        self.metrics.inc("state_full_rebuilds")
        span.count("full_rebuild")
        with span.child("sync"):
            # Counted cache-miss fallback (state_full_rebuilds); the
            # bind_from_cache/delta publication keeps this off the
            # per-verb path.
            state = full_sync(
                self.api,
                cost_for_generation=self.config.cost_model,
                assume_ttl_s=self.config.assume_ttl_s,
                clock=self.clock,
            )
        with self._cache_lock:
            self._cached_state = state
            self._cached_at = self.clock()
            self._cached_informer_version = None  # not informer-coherent
        return state

    # ---- sort (Prioritize) -------------------------------------------------

    #: Memo-economics counters snapshotted around a traced verb so its
    #: explain record reports per-decision memo hits, not lifetime totals.
    _MEMO_COUNTERS = ("score_memo_hits", "gang_ctx_memo_hits",
                      "gang_plan_reuse_hits", "gang_candidate_memo_hits")

    def _memo_counter_snapshot(self) -> tuple[int, ...]:
        c = self.metrics.counters
        return tuple(c.get(name, 0) for name in self._MEMO_COUNTERS)

    def _memo_delta(self, base: tuple[int, ...]) -> dict[str, int]:
        c = self.metrics.counters
        return {name: d for name, b in zip(self._MEMO_COUNTERS, base)
                if (d := c.get(name, 0) - b)}

    @staticmethod
    def _gang_explain(gang: tuple[str, str, int],
                      gang_ctx: dict | None) -> dict:
        """The gang-search block of an explain record: identity, search
        stats (compositions considered, plan reuse), and the chosen plan's
        node order."""
        out: dict = {"id": gang[1], "size": gang[2],
                     "feasible": gang_ctx is not None}
        if gang_ctx is not None:
            out.update(gang_ctx.get("stats", {}))
            out["plan_nodes"] = list(gang_ctx["order"])
        return out

    def _zero_score_reason(self, state: ClusterState, k: int,
                           name: str) -> str:
        """Why a non-gang node scored 0 — re-derived on the traced path
        only (the score loop itself stays branch-lean)."""
        dom = state.domain_of_node(name)
        if dom is None:
            return "not_a_tpu_node"
        if state.free_mask_on_node(name).bit_count() < k:
            return "insufficient_free_chips"
        return "no_contiguous_placement"

    @staticmethod
    def _plan_domains(state: ClusterState, plan) -> set[str]:
        """ICI domains a gang plan's nodes live in — THE shared derivation
        for every explain rejection-reason site, so sort and bind explains
        can never disagree on what counts as a domain mismatch."""
        return {d.slice_id for n in plan
                if (d := state.domain_of_node(n)) is not None}

    #: Detailed per-node rejection entries retained per explain record.
    #: Planned/chosen/scored nodes are always listed; rejections past the
    #: cap collapse into a ``nodes_omitted`` count — on a thousands-node
    #: fleet an explain record must stay KB-sized, not O(cluster).
    _EXPLAIN_REJECT_CAP = 256

    def _gang_reject_reason(self, state: ClusterState, k: int, name: str,
                            gang_ctx: dict,
                            plan_doms: set[str] | None = None) -> str:
        """Why a node is outside a feasible gang's plan (traced path)."""
        dom = state.domain_of_node(name)
        if dom is None:
            return "not_a_tpu_node"
        if plan_doms is None:
            plan_doms = self._plan_domains(state, gang_ctx["plan"])
        if plan_doms and dom.slice_id not in plan_doms:
            return "gang_domain_mismatch"
        if state.free_mask_on_node(name).bit_count() < k:
            return "insufficient_free_chips"
        return "not_in_gang_plan"

    def sort(self, pod: dict, node_names: list[str]) -> list[dict]:
        """Score candidate nodes for a pod; [{"Host": ..., "Score": 0-10}].

        The reference's per-node loop (design.md:119: best combo per node,
        then the score formula — with the direction fixed, SURVEY.md §5).
        Traced: phase spans (state / gang_plan / score) plus an explain
        record with the per-node score-or-rejection breakdown.
        """
        t0 = self._wall()
        self.metrics.inc("sort_requests")
        md = pod.get("metadata", {})
        tr = self.tracer.start(
            "sort",
            pod=f"{md.get('namespace', 'default')}/{md.get('name', '?')}")
        with tr:
            out = self._sort_spanned(pod, node_names, tr)
        self.metrics.observe_ms("sort", (self._wall() - t0) * 1e3)
        return out

    def _sort_spanned(self, pod: dict, node_names: list[str],
                      tr) -> list[dict]:
        # Decide the read source ONCE: state sync and gang-member lookup
        # must see the same view (cache during sort, API during bind) — a
        # second synced check could flip between the two reads if a Gone
        # clears the informer mid-sort.
        informer_reader = (self.informer if self.informer is not None
                           and self.informer.synced else None)
        memo_base = self._memo_counter_snapshot() if tr.enabled else None
        with tr.phase("state") as sp:
            state = self._state(allow_cache=True, reader=informer_reader,
                                span=sp)
        k = ko.pod_requested_chips(pod)
        gang = _gang_of(pod)
        wanted_gen = _wanted_generation(pod)
        gang_ctx = None
        if k > 0 and gang is not None:
            # One plan per sort request — the plan depends only on state and
            # the gang, never on the candidate node being scored.
            with tr.phase("gang_plan") as sp:
                gang_ctx = self._gang_context(
                    state, gang, k, wanted_gen,
                    reader=informer_reader or self.api, pod=pod)
                if gang_ctx is not None:
                    sp.count("planned_nodes", len(gang_ctx["plan"]))
        explain_nodes: list[dict] | None = [] if tr.enabled else None
        plan_doms: set[str] | None = None
        if explain_nodes is not None and gang_ctx is not None:
            plan_doms = self._plan_domains(state, gang_ctx["plan"])
        rejects_kept = rejects_omitted = 0
        # Batch index reads for the non-gang score loop: the per-``k``
        # bucket is resolved ONCE per sort and hits are counted locally
        # (one metrics.inc at the end) — at fleet scale the loop runs
        # O(nodes) times per member and the per-node method call plus
        # counter increment were a measured slice of the sort tail.
        kd = None
        hits = 0
        if self.SCORE_INDEX and gang is None and k > 0:
            kd = self._score_index_for(state, k)
        # Untraced fast paths: no explain bookkeeping and no generation
        # pin means the per-node loop needs no branches at all — a gang
        # sort's per-node rank scores are precomputed over the plan
        # (O(plan) instead of O(nodes) calls: planned nodes are the only
        # nonzero scores), and a single-pod sort is one index read per
        # node.  Scores, index content, and hit counters are identical
        # to the slow loop below — the fleet trace spends ~70k sorts per
        # run in exactly this shape, where per-node call overhead was
        # the measured sort-tail floor.
        fast = explain_nodes is None and k > 0 and wanted_gen is None
        out = []
        with tr.phase("score") as sp:
            if fast and gang is not None:
                gang_scores = ({n: self._score_gang_node(gang_ctx, n)
                                for n in gang_ctx["order"]}
                               if gang_ctx is not None else {})
                gs_get = gang_scores.get
                out = [{"Host": n, "Score": gs_get(n, 0)}
                       for n in node_names]
                sp.count("nodes", len(node_names))
                return out
            if fast and kd is not None:
                kd_get = kd.get
                uncached = self._score_node_uncached
                ap = out.append
                for name in node_names:
                    score = kd_get(name)
                    if score is None:
                        score = kd[name] = uncached(state, k, name)
                    else:
                        hits += 1
                    ap({"Host": name, "Score": score})
                sp.count("nodes", len(node_names))
                if hits:
                    self.metrics.inc("score_memo_hits", hits)
                return out
            for name in node_names:
                score = 0
                reason = None
                memo_hit = None
                if k <= 0:
                    reason = "no_chips_requested"
                elif not self._generation_ok(state, name, wanted_gen):
                    reason = "wrong_generation"
                elif gang is not None:
                    score = self._score_gang_node(gang_ctx, name)
                    if (score == 0 and explain_nodes is not None
                            and rejects_kept < self._EXPLAIN_REJECT_CAP):
                        reason = ("gang_infeasible" if gang_ctx is None
                                  else self._gang_reject_reason(
                                      state, k, name, gang_ctx, plan_doms))
                elif kd is not None:
                    if explain_nodes is not None:
                        memo_hit = name in kd
                    score = kd.get(name)
                    if score is None:
                        score = kd[name] = self._score_node_uncached(
                            state, k, name)
                    else:
                        hits += 1
                    if (score == 0 and explain_nodes is not None
                            and rejects_kept < self._EXPLAIN_REJECT_CAP):
                        reason = self._zero_score_reason(state, k, name)
                else:
                    if explain_nodes is not None:
                        memo = getattr(state, "_score_memo", None)
                        memo_hit = (memo is not None
                                    and (k, name) in memo)
                    score = self._score_node(state, k, name)
                    if (score == 0 and explain_nodes is not None
                            and rejects_kept < self._EXPLAIN_REJECT_CAP):
                        reason = self._zero_score_reason(state, k, name)
                out.append({"Host": name, "Score": score})
                if explain_nodes is not None:
                    if score == 0:
                        if rejects_kept >= self._EXPLAIN_REJECT_CAP:
                            rejects_omitted += 1
                            continue
                        rejects_kept += 1
                    e: dict = {"node": name, "score": score}
                    if memo_hit is not None:
                        e["memo_hit"] = memo_hit
                    if reason is not None:
                        e["rejected"] = reason
                    explain_nodes.append(e)
            sp.count("nodes", len(node_names))
        if hits:
            self.metrics.inc("score_memo_hits", hits)
        if tr.enabled:
            md = pod.get("metadata", {})
            record = {
                "verb": "sort",
                "pod": f"{md.get('namespace', 'default')}"
                       f"/{md.get('name', '?')}",
                "t": round(tr.t, 6),
                "k": k,
                "gang": (self._gang_explain(gang, gang_ctx)
                         if gang is not None else None),
                "nodes": explain_nodes,
                "memo": self._memo_delta(memo_base),
            }
            if rejects_omitted:
                record["nodes_omitted"] = rejects_omitted
            tr.explain(record)
        return out

    def sort_best(self, pod: dict, node_names: list[str]) -> dict | None:
        """The sort verb reduced to its winner: the ``{"Host", "Score"}``
        entry a ``max(sort(...), key=BEST_SCORE_KEY)`` would select, or
        None when nothing scores positive (which every placement consumer
        treats exactly like an empty candidate list).  Traced schedulers,
        kill-switched score indexes, zero-chip pods, and generation pins
        all DELEGATE to :meth:`sort` — explain records, phase spans, and
        every counter stay byte-for-byte the verb's.  The untraced
        steady-state shape skips materializing the O(nodes) score list:
        a gang sort reads only the plan's rank scores, a single-pod sort
        streams the score index — same index content, same
        ``score_memo_hits``, same winner.  The sim's placement loop is
        the consumer: at fleet saturation it was building (and max-ing
        over) ~70M score dicts per run."""
        k = ko.pod_requested_chips(pod)
        if (self.tracer.enabled or not self.SCORE_INDEX or k <= 0
                or _wanted_generation(pod) is not None):
            scores = self.sort(pod, node_names)
            return max(scores, key=BEST_SCORE_KEY) if scores else None
        t0 = self._wall()
        self.metrics.inc("sort_requests")
        informer_reader = (self.informer if self.informer is not None
                           and self.informer.synced else None)
        state = self._state(allow_cache=True, reader=informer_reader)
        gang = _gang_of(pod)
        best_s = 0
        best_n: str | None = None
        if gang is not None:
            gang_ctx = self._gang_context(
                state, gang, k, None,
                reader=informer_reader or self.api, pod=pod)
            if gang_ctx is not None:
                for n in gang_ctx["order"]:
                    s = self._score_gang_node(gang_ctx, n)
                    if s > best_s:
                        best_s, best_n = s, n
                    elif s and s == best_s and n > best_n:
                        best_n = n
                if best_n is not None and best_n not in node_names:
                    # A planned node outside the candidate list (not a
                    # sim shape — plans come from the same alive state):
                    # recompute the max over the actual candidates, with
                    # the same (Score, Host) tie-break as everywhere.
                    gs = {n: self._score_gang_node(gang_ctx, n)
                          for n in gang_ctx["order"]}
                    best_s, best_n = 0, None
                    for n in node_names:
                        s = gs.get(n, 0)
                        if s > best_s:
                            best_s, best_n = s, n
                        elif s and s == best_s and n > best_n:
                            best_n = n
        else:
            kd = self._score_index_for(state, k)
            kd_get = kd.get
            uncached = self._score_node_uncached
            hits = 0
            for name in node_names:
                s = kd_get(name)
                if s is None:
                    s = kd[name] = uncached(state, k, name)
                else:
                    hits += 1
                if s > best_s:
                    best_s, best_n = s, name
                elif s and s == best_s and name > best_n:
                    best_n = name
            if hits:
                self.metrics.inc("score_memo_hits", hits)
        self.metrics.observe_ms("sort", (self._wall() - t0) * 1e3)
        if best_s <= 0 or best_n is None:
            return None
        return {"Host": best_n, "Score": best_s}

    def _generation_ok(self, state: ClusterState, node_name: str,
                       wanted: str | None) -> bool:
        if wanted is None:
            return True
        dom = state.domain_of_node(node_name)
        return dom is not None and dom.topology.generation.name == wanted

    def _score_index_for(self, state: ClusterState, k: int) -> dict[str, int]:
        """The per-``k`` node->score bucket of the state's incremental
        score index (SCORE_INDEX shape), created lazily.  The index lives
        on the state instance, so it can never outlive the occupancy it
        was computed from: full rebuilds start empty, COW replacements
        carry it filtered (:meth:`_carry_state_memos`), and single-owner
        in-place folds evict exactly the changed domains' nodes
        (:meth:`_evict_state_memos`)."""
        idx = getattr(state, "_score_index", None)
        if idx is None:
            idx = state._score_index = {}
        kd = idx.get(k)
        if kd is None:
            kd = idx[k] = {}
        return kd

    def _score_node(self, state: ClusterState, k: int, node_name: str) -> int:
        # Memoized on the state instance: a wave of same-sized pods sorts
        # back-to-back against one derived state (the informer-version
        # cache), and a node's score depends only on (state, k, node).
        # States are replaced wholesale (rebuild or bind delta clone), so
        # the memo can never outlive the facts it was computed from.
        if self.SCORE_INDEX:
            kd = self._score_index_for(state, k)
            got = kd.get(node_name)
            if got is None:
                got = kd[node_name] = self._score_node_uncached(
                    state, k, node_name)
            else:
                self.metrics.inc("score_memo_hits")
            return got
        memo = getattr(state, "_score_memo", None)
        if memo is None:
            memo = state._score_memo = {}
        key = (k, node_name)
        got = memo.get(key)
        if got is None:
            got = memo[key] = self._score_node_uncached(state, k, node_name)
        else:
            self.metrics.inc("score_memo_hits")
        return got

    def _score_node_uncached(self, state: ClusterState, k: int,
                             node_name: str) -> int:
        dom = state.domain_of_node(node_name)
        if dom is None:
            return 0
        node_mask = dom.node_masks.get(node_name, 0)
        node_free_mask = node_mask & dom.allocator.free_mask
        if node_free_mask.bit_count() < k:
            return 0
        placement = dom.allocator.find(
            k, free_mask=node_free_mask, within_mask=node_mask)
        if placement is None:
            return 0
        if k == 1:
            # Anti-fragmentation quality: fewer free neighbors around the
            # chosen chip is better (Singular policy, Gaia PDF Alg. 3).
            chip = placement.chips[0]
            degree = max(1, len(dom.topology.neighbors(chip)))
            free_n = dom.allocator.free_neighbor_count(chip)
            return max(1, round(MAX_PRIORITY * (1 - free_n / (degree + 1))))
        ideal = self._ideal_gbps(dom, k)
        if ideal <= 0:
            return 1
        frac = min(1.0, placement.score_gbps / ideal)
        return max(1, round(MAX_PRIORITY * frac))

    def _ideal_gbps(self, dom: SliceDomain, k: int) -> float:
        shapes = enumerate_shapes(dom.topology, k, dom.allocator.cost)
        if not shapes:
            return dom.allocator.cost.ici_link_gbps  # blob-only request size
        return predict_allreduce_gbps(dom.topology, shapes[0].dims,
                                      dom.allocator.cost)

    def batch_scores(self, k: int,
                     node_names: list[str]) -> tuple[dict[str, int],
                                                     tuple | None]:
        """The ``{node: score}`` map for ``k``-chip members over
        ``node_names`` — the batch planner's scoring primitive
        (tputopo.batch) — plus a changed-node report: None when every
        entry must be treated as new (first fill of this bucket, or a
        rebuilt/carried state whose fill bookkeeping did not survive),
        else the sorted tuple of node names whose scores moved since the
        previous report (empty when none did).  The first call streams
        the persistent score-index bucket full exactly like
        :meth:`sort_best`'s fill; after that only the nodes the in-place
        fold eviction marked dirty (:meth:`_evict_state_memos`) are
        rescored — O(changed nodes) per wake instead of a fleet-size
        scan, which was the batch wake's dominant cost at 1024 nodes.
        The bucket is returned whole; entries for nodes outside
        ``node_names`` (dead nodes, earlier fills) are harmless —
        consumers read only the nodes they ask about, and a dead node's
        dirty marker is simply refilled along with the rest."""
        informer_reader = (self.informer if self.informer is not None
                           and self.informer.synced else None)
        state = self._state(allow_cache=True, reader=informer_reader)
        uncached = self._score_node_uncached
        if not self.SCORE_INDEX:
            return ({name: uncached(state, k, name)
                     for name in node_names}, None)
        kd = self._score_index_for(state, k)
        filled = getattr(state, "_batch_filled", None)
        if filled is None:
            filled = state._batch_filled = {}
        dirty = filled.get(k)
        if dirty is None:
            kd_get = kd.get
            hits = 0
            for name in node_names:
                if kd_get(name) is None:
                    kd[name] = uncached(state, k, name)
                else:
                    hits += 1
            if hits:
                self.metrics.inc("score_memo_hits", hits)
            filled[k] = set()
            return kd, None
        changed = tuple(sorted(dirty))
        if changed:
            for name in changed:
                kd[name] = uncached(state, k, name)
            dirty.clear()
        hits = len(node_names) - len(changed)
        if hits > 0:
            self.metrics.inc("score_memo_hits", hits)
        return kd, changed

    # ---- gang planning -----------------------------------------------------

    def _gang_members(self, namespace: str, gang_id: str,
                      reader=None, state: ClusterState | None = None) -> list[dict]:
        """List a gang's member pods.  When ``state`` is given the result is
        memoized on it: one bind/sort evaluates the same gang several times
        (plan reuse validation, planning, the fully-bound guard, release),
        and each un-memoized call is a full client-side-filtered LIST."""
        if state is not None:
            memo = getattr(state, "_gang_members_memo", None)
            if memo is None:
                memo = state._gang_members_memo = {}
            key = (namespace, gang_id,
                   id(reader) if reader is not None else None)
            if key not in memo:
                memo[key] = self._gang_members(namespace, gang_id, reader)
            return memo[key]

        def is_member(p: dict) -> bool:
            return (
                p["metadata"].get("namespace", "default") == namespace
                and ({**p["metadata"].get("annotations", {}),
                      **p["metadata"].get("labels", {})}
                     ).get(LABEL_GANG_ID) == gang_id
            )

        src = reader or self.api
        # O(gang) fast path: the fake API and the informer mirror both
        # maintain a merged-meta equality index over the gang-id key
        # (fakeapi.INDEXED_META), so membership is an index lookup instead
        # of a client-side filtered LIST over every pod (~580k is_member
        # calls per standard sim trace before this).  ``copy=False`` only
        # against the mirror (entries replaced wholesale — safe snapshot);
        # the authoritative server may have concurrent in-place patchers,
        # so it deepcopies the O(gang) result.  The REST client has no
        # index — the filtered LIST below stays its path.
        fast = getattr(src, "list_by_meta", None)
        if fast is not None:
            try:
                members = fast("pods", LABEL_GANG_ID, gang_id,
                               copy=reader is None)
            except (KeyError, TypeError):
                members = None
            if members is not None:
                return [p for p in members
                        if p["metadata"].get("namespace", "default")
                        == namespace]
        try:
            # Copy-free when the reader supports it (the informer mirror,
            # whose stored objects are replaced wholesale, never mutated):
            # every consumer of a member list is read-only, and the deepcopy
            # of the whole pod population per gang evaluation dominated the
            # bind path at fleet scale.
            # tpulint: disable=nocopy-flow -- documented read-only member lists (the comment above); the runtime digest guard enforces the contract in guarded runs
            return src.list("pods", is_member, copy=False)
        except TypeError:  # reader without a copy kwarg (fake/REST client)
            return src.list("pods", is_member)

    # ---- vectorized gang screen (VECTOR_GANG_PLAN) -------------------------

    def _vector_rows(self, dom: SliceDomain) -> tuple:
        """(bit->row int32 array, row_by_node, nrows) for one domain —
        which node each chip-bit belongs to, as numpy rows.  Cached on
        the domain's node-mask table identity: those dicts are built at
        sync and shared across copy-on-write states, so the layout
        survives every fold/delta until a full rebuild replaces them.
        Bits of no listed node (delisted hosts) go to a trash row that
        still participates in per-domain sums — every distortion is
        toward OVER-admitting a domain, never rejecting one."""
        key = id(dom.node_masks)
        with self._cache_lock:
            got = self._vector_rows_cache.get(key)
        if got is not None and got[0] is dom.node_masks:
            return got[1]
        import numpy as np

        nchips = len(dom.topology.chips)
        names = sorted(dom.node_masks)
        trash = len(names)
        rows = np.full(((nchips + 7) // 8) * 8, trash, dtype=np.int32)
        row_by_node = {}
        for r, n in enumerate(names):
            rows[mask_bits_array(dom.node_masks[n], nchips)
                 .astype(bool)] = r
            row_by_node[n] = r
        layout = (rows, row_by_node, trash + 1)
        with self._cache_lock:
            self._vector_rows_cache[key] = (dom.node_masks, layout)
            while len(self._vector_rows_cache) > self._GANG_PLAN_CACHE_MAX:
                self._vector_rows_cache.pop(
                    next(iter(self._vector_rows_cache)))
        return layout

    def _vector_patch(self, state: ClusterState, got: tuple,
                      stale: set) -> tuple | None:
        """Refresh the stale domains' windows of the count batch in
        place — one small unpackbits+bincount per moved domain — and
        fix the per-k capacity memo for exactly those domains.  In-
        place folds only queue domain ids (_evict_state_memos); the
        cost lands here, per READ, so a burst of folds between gang
        plans collapses into one patch.  Returns None on any layout
        mismatch (a domain the batch never saw, a replaced node-mask
        table, node churn) — the caller rebuilds wholesale."""
        import numpy as np

        counts, info = got
        for sid in stale:
            win = info.get(sid)
            dom = state.domains.get(sid)
            if win is None or dom is None:
                return None
            r0, nr, _ = win
            rows, _, nrows = self._vector_rows(dom)
            if nrows != nr:
                return None
            bits = np.unpackbits(
                np.frombuffer(dom.allocator.free_mask_bytes(),
                              dtype=np.uint8), bitorder="little")
            counts[r0:r0 + nr] = np.bincount(rows, weights=bits,
                                             minlength=nr)
        memo = getattr(state, "_vector_capk", None)
        if memo is not None:
            for k, caps in memo.items():
                for sid in stale:
                    if sid in caps:
                        r0, nr, _ = info[sid]
                        caps[sid] = int((counts[r0:r0 + nr] >= k).sum())
        xmemo = getattr(state, "_vector_capx", None)
        if xmemo is not None:
            # Exclude-keyed caps (VECTOR_CAP_MEMO) of a moved domain are
            # stale in every entry; drop just those — the next probe
            # recomputes them from the freshly patched windows.
            for entry in xmemo.values():
                for sid in stale:
                    entry.pop(sid, None)
        stale.clear()
        return got

    def _vector_counts(self, state: ClusterState) -> tuple:
        """(counts, info) — per-node free-chip counts for EVERY domain
        in one flat array, from a single unpackbits+bincount batch over
        the concatenated free masks; ``info`` maps slice_id to its
        (row offset, row count, row_by_node) window.  Memoized on the
        state instance: one batch serves every gang planned against
        that occupancy, which under a saturated queue is many; in-place
        folds queue their changed domains and this read patches those
        windows before answering."""
        got = getattr(state, "_vector_counts_cache", None)
        if got is not None:
            stale = getattr(state, "_vector_stale", None)
            if not stale:
                return got
            patched = self._vector_patch(state, got, stale)
            if patched is not None:
                return patched
            # Layout moved under the cache: drop everything derived
            # from it and fall through to the wholesale rebuild.
            stale.clear()
            for attr in ("_vector_counts_cache", "_vector_capk",
                         "_vector_capx"):
                if getattr(state, attr, None) is not None:
                    delattr(state, attr)
        import numpy as np

        doms = sorted(state.domains.values(), key=lambda d: d.slice_id)
        payload = bytearray()
        chunks = []
        info: dict[str, tuple] = {}
        row0 = 0
        for d in doms:
            rows, row_by_node, nrows = self._vector_rows(d)
            payload += d.allocator.free_mask_bytes()
            chunks.append(rows + np.int32(row0))
            info[d.slice_id] = (row0, nrows, row_by_node)
            row0 += nrows
        if not doms:
            got = (np.zeros(0, dtype=np.int64), info)
        else:
            bits = np.unpackbits(
                np.frombuffer(bytes(payload), dtype=np.uint8),
                bitorder="little")
            counts = np.bincount(np.concatenate(chunks), weights=bits,
                                 minlength=row0).astype(np.int64)
            got = (counts, info)
        state._vector_counts_cache = got
        return got

    def _vector_cap(self, state: ClusterState, dom: SliceDomain, k: int,
                    exclude_nodes: set[str],
                    exclude_key: frozenset | None = None) -> int | None:
        """Upper bound on the gang members ``dom`` can host at ``k``
        chips each: nodes with >= k free chips, minus already-consumed
        (excluded) hosts, from the vectorized count batch.  Per-(state,
        k) capacities are memoized; None when the domain is unknown to
        the batch (callers fall back to probing).  ``exclude_key`` is an
        optional precomputed ``frozenset(exclude_nodes)`` so repeat
        callers (the gang screen probes every domain with one exclude
        set) don't rebuild it per domain for the VECTOR_CAP_MEMO key."""
        # Read the batch FIRST, unconditionally: it patches any windows
        # (and per-k / per-exclude caps) staled by in-place folds since
        # the last read — a memo hit must never answer from a pre-fold
        # capacity.
        counts, info = self._vector_counts(state)
        if self.VECTOR_CAP_MEMO:
            if exclude_key is None:
                exclude_key = frozenset(exclude_nodes)
            xmemo = getattr(state, "_vector_capx", None)
            if xmemo is None:
                xmemo = state._vector_capx = {}
            entry = xmemo.get((k, exclude_key))
            if entry is None:
                if len(xmemo) >= self._GANG_PLAN_CACHE_MAX:
                    xmemo.clear()  # bound pathological exclude-set churn
                entry = xmemo[(k, exclude_key)] = {}
            elif dom.slice_id in entry:
                self.metrics.inc("vector_cap_memo_hits")
                return entry[dom.slice_id]
        else:
            entry = None
        memo = getattr(state, "_vector_capk", None)
        if memo is None:
            memo = state._vector_capk = {}
        caps = memo.get(k)
        if caps is None:
            ge = counts >= k
            caps = memo[k] = {sid: int(ge[r0:r0 + nr].sum())
                              for sid, (r0, nr, _) in info.items()}
        cap = caps.get(dom.slice_id)
        if cap is not None and exclude_nodes:
            r0, _, row_by_node = info[dom.slice_id]
            for n in exclude_nodes:
                r = row_by_node.get(n)
                if r is not None and counts[r0 + r] >= k:
                    cap -= 1
        if entry is not None:
            entry[dom.slice_id] = cap
        return cap

    def _mask_probe_vocab(self, dom: SliceDomain, k: int) -> tuple | None:
        """Candidate vocabulary for the mask-native gang probe: every box
        of every k-volume shape inside each node's chip mask, with the
        exact ordering key ``Allocator._pick_box`` minimizes flattened
        into one int per candidate.  The key is ``(score rank, frag,
        chips)`` lexicographically; score rank is dense over DISTINCT
        shape scores (ties compete on the rest, as the strict-< min
        does), frag is the only occupancy-dependent term, and the chips
        tiebreak becomes a per-host position: candidates sorted by
        (chips tuple, encounter order), so exact key ties resolve to the
        first-encountered candidate exactly as strict-< keeps it.
        Cached per (node-mask table identity, k); None when no k-volume
        box fits the topology at all (every probe needs the exact
        walk's blob fallback)."""
        key = (id(dom.node_masks), k)
        with self._cache_lock:
            got = self._mask_probe_cache.get(key)
        if got is not None and got[0] is dom.node_masks:
            return got[1]
        import numpy as np

        topo = dom.topology
        cost = dom.allocator.cost
        nchips = len(topo.chips)
        shapes = enumerate_shapes(topo, k, cost)
        vocab: tuple | None = None
        if shapes:
            ranked = []  # (rank, score, dims) — dense rank, best first
            rank, prev = -1, None
            for s in shapes:
                sc = predict_allreduce_gbps(topo, s.dims, cost)
                if prev is None or sc < prev:
                    rank, prev = rank + 1, sc
                ranked.append((rank, sc, s.dims))
            hosts = []      # (host, node_name, node_mask, seg_lo, seg_hi)
            masks, nbrs, ranks, poss = [], [], [], []
            placements: list[Placement] = []
            for host, node_name in dom.node_by_host.items():
                node_mask = dom.node_masks.get(node_name, 0)
                lo = len(masks)
                entries = []  # (chips, encounter, rank, score, origin, dims,
                enc = 0       #  box_mask, nbr_mask & node_mask)
                for rk, sc, dims in ranked:
                    for o, chips, mask, nbr in _boxes_within(topo, dims,
                                                             node_mask):
                        entries.append((chips, enc, rk, sc, o, dims, mask,
                                        nbr & node_mask))
                        enc += 1
                order = sorted(range(len(entries)),
                               key=lambda i: entries[i][:2])
                pos = [0] * len(entries)
                for p_i, i in enumerate(order):
                    pos[i] = p_i
                for (chips, _, rk, sc, o, dims, mask, nbrm), p_i in zip(
                        entries, pos):
                    masks.append(mask)
                    nbrs.append(nbrm)
                    ranks.append(rk)
                    poss.append(p_i)
                    placements.append(Placement(chips=chips, origin=o,
                                                dims=dims, score_gbps=sc))
                hosts.append((host, node_name, node_mask, lo, len(masks)))
            if masks:
                nbits = ((nchips + 7) // 8) * 8
                m2 = len(masks) + 1            # > any pos
                m1 = (nbits + 1) * m2          # > any frag * m2 + pos
                big = (max(ranks) + 1) * m1    # > any feasible key
                mask_bits = np.stack([mask_bits_array(m, nchips)
                                      for m in masks]).astype(np.int64)
                nbr_bits = np.stack([mask_bits_array(m, nchips)
                                     for m in nbrs]).astype(np.int64)
                key_base = (np.asarray(ranks, dtype=np.int64) * m1
                            + np.asarray(poss, dtype=np.int64))
                vocab = (hosts, mask_bits, nbr_bits, key_base,
                         np.int64(m2), np.int64(big), placements, nchips)
        with self._cache_lock:
            self._mask_probe_cache[key] = (dom.node_masks, vocab)
            while len(self._mask_probe_cache) > self._GANG_PLAN_CACHE_MAX:
                self._mask_probe_cache.pop(
                    next(iter(self._mask_probe_cache)))
        return vocab

    def _mask_probe_candidates(self, dom: SliceDomain, k: int,
                               exclude_nodes: set[str]
                               ) -> dict[Coord, Placement]:
        """Mask-native per-host candidate map (MASK_GANG_PROBE, k >= 2):
        one numpy feasibility/fragmentation pass over the domain's whole
        candidate vocabulary answers every host's best-box query; hosts
        whose free chips defeat every vocabulary box (or a k with no box
        vocabulary) fall back to the exact ``Allocator.find`` walk.
        Produces the same {host: placement} map as the per-host walk —
        feasibility, fragmentation, and every tiebreak replicate
        ``_pick_box`` bit-for-bit (see ``_mask_probe_vocab``)."""
        vocab = self._mask_probe_vocab(dom, k)
        free_mask = dom.allocator.free_mask
        candidate: dict[Coord, Placement] = {}
        fired = fell_back = 0
        if vocab is not None:
            import numpy as np

            hosts, mask_bits, nbr_bits, key_base, m2, big, placements, \
                nchips = vocab
            fbits = mask_bits_array(free_mask, nchips).astype(np.int64)
            hits = mask_bits @ fbits
            keys = np.where(hits == k, key_base + (nbr_bits @ fbits) * m2,
                            big)
        else:
            hosts = [(host, node_name, dom.node_masks.get(node_name, 0),
                      0, 0) for host, node_name in dom.node_by_host.items()]
            keys = big = None
        for host, node_name, node_mask, lo, hi in hosts:
            if node_name in exclude_nodes:
                continue
            node_free_mask = node_mask & free_mask
            if node_free_mask.bit_count() < k:
                continue
            p = None
            if hi > lo:
                seg = keys[lo:hi]
                i = int(seg.argmin())
                if seg[i] < big:
                    p = placements[lo + i]
                    fired += 1
            if p is None:
                # Fragmented remainder (blob territory) or no vocabulary
                # at this k: the exact walk is authoritative.
                p = dom.allocator.find(
                    k, free_mask=node_free_mask, within_mask=node_mask)
                fell_back += 1
            if p is not None:
                candidate[host] = p
        if fired:
            self.metrics.inc("gang_mask_probe_hits", fired)
        if fell_back:
            self.metrics.inc("gang_mask_probe_fallbacks", fell_back)
        return candidate

    def _plan_gang(self, state: ClusterState, dom: SliceDomain,
                   replicas: int, k: int,
                   exclude_nodes: set[str],
                   exclude_key: frozenset | None = None
                   ) -> dict[str, Placement] | None:
        """Plan ``replicas`` single-node k-chip placements, preferring a
        contiguous box on the host grid so the union is ICI-contiguous
        (SURVEY.md §7: Link-scheduler analog in 3D).  Returns
        {node_name: placement} or None when the gang cannot fit."""
        # Free-volume pre-gate: every member needs k distinct chips, so a
        # domain with fewer than replicas*k free chips TOTAL can never
        # host the gang — answer None before building candidate maps or
        # the host-grid allocator.  At fleet saturation most domains fail
        # here, which is what keeps a deeply queued gang's per-wake
        # replan from walking every host of every domain.
        if dom.allocator.free_count < replicas * k:
            return None
        topo = dom.topology
        hb = topo.generation.host_bounds
        grid_dims = tuple(max(1, d // b) for d, b in zip(topo.dims, hb))
        host_grid = _host_grid(topo.generation, grid_dims, topo.wrap)

        # Per-host candidate map, memoized on the state instance: it
        # depends only on (state occupancy, domain, k, exclude), and the
        # multislice composition search probes the same key for every
        # feasible replica count m — without the memo, max_feasible re-ran
        # allocator.find across every host per probe.  States are replaced
        # wholesale (rebuild, event fold, bind delta), so the memo can
        # never outlive the occupancy it was computed from.
        memo = getattr(state, "_gang_cand_memo", None)
        if memo is None:
            memo = state._gang_cand_memo = {}
        memo_key = (dom.slice_id, k,
                    frozenset(exclude_nodes) if exclude_key is None
                    else exclude_key)
        candidate = memo.get(memo_key)
        if candidate is None:
            if self.MASK_GANG_PROBE and k >= 2:
                candidate = self._mask_probe_candidates(dom, k, exclude_nodes)
            else:
                candidate = {}
                free_mask = dom.allocator.free_mask
                for host, node_name in dom.node_by_host.items():
                    if node_name in exclude_nodes:
                        continue
                    node_mask = dom.node_masks.get(node_name, 0)
                    node_free_mask = node_mask & free_mask
                    if node_free_mask.bit_count() < k:
                        continue
                    p = dom.allocator.find(
                        k, free_mask=node_free_mask, within_mask=node_mask)
                    if p is not None:
                        candidate[host] = p
            memo[memo_key] = candidate
            # Per-domain key index for dirty-set eviction (DIRTY_FOLD).
            # Maintained unconditionally — a mid-run switch flip must
            # never see a partial index — and only ever consulted to POP
            # keys, so a stale entry naming an already-evicted key is a
            # harmless no-op.
            by_dom = getattr(state, "_gang_cand_by_dom", None)
            if by_dom is None:
                by_dom = state._gang_cand_by_dom = {}
            by_dom.setdefault(dom.slice_id, set()).add(memo_key)
        else:
            self.metrics.inc("gang_candidate_memo_hits")

        if len(candidate) < replicas:
            return None
        host_alloc = Allocator(host_grid, dom.allocator.cost)
        host_alloc.mark_used([h for h in host_grid.chips if h not in candidate])
        hosts = host_alloc.find(replicas)
        if hosts is None:
            return None
        return {dom.node_by_host[h]: candidate[h] for h in hosts.chips}

    @staticmethod
    def _gang_allows_multislice(members: list[dict]) -> bool:
        for p in members:
            if ExtenderScheduler.BIND_ANN_TEMPLATE:
                allow = _pod_meta_get(p["metadata"], LABEL_ALLOW_MULTISLICE)
            else:
                meta = {**p["metadata"].get("annotations", {}),
                        **p["metadata"].get("labels", {})}
                allow = meta.get(LABEL_ALLOW_MULTISLICE)
            if allow == "true":
                return True
        return False

    @staticmethod
    def _union_requesting_pod(members: list[dict], pod: dict | None) -> list[dict]:
        """Ensure the pod the verb is serving appears in its gang's member
        list: the list comes from the (eventually consistent) informer
        mirror, and a just-created pod's ADDED event may not have landed yet
        — without this, a fresh gang's first sort could miss its own labels
        (e.g. allow-multislice) and report the gang infeasible."""
        if pod is None:
            return members
        md = pod["metadata"]
        key = (md.get("namespace", "default"), md["name"])
        for p in members:
            pmd = p["metadata"]
            if (pmd.get("namespace", "default"), pmd["name"]) == key:
                return members
        return members + [pod]

    def _gang_context(self, state: ClusterState, gang: tuple[str, str, int],
                      k: int, wanted_gen: str | None = None,
                      reader=None, pod: dict | None = None) -> dict | None:
        """Remaining-member plan for a gang, given already-bound members.

        Returns {"plan": {node: Placement}, "order": [node, ...]} or None
        when the gang cannot fit.  One ICI-contiguous domain is always
        preferred; gangs labeled tpu.dev/allow-multislice=true may split
        across domains (replica sync rides DCN between slices) when no
        single domain has room.

        Memoized on the ``state`` instance: sorting an N-member gang calls
        this once per member against the same derived state, and the state
        object is rebuilt whenever the cluster mirror changes (the
        informer-version cache key in ``_state``), so the memo can never
        outlive the facts it was computed from."""
        namespace, gang_id, size = gang
        memo = getattr(state, "_gang_ctx_memo", None)
        if memo is None:
            memo = state._gang_ctx_memo = {}
        # id(reader), not `reader is None`: two distinct informer readers
        # against one state instance must not share cached member lists
        # (ADVICE r2).  The id is safe as a key because the memo lives on
        # the state object, whose lifetime the reader outlives.
        #
        # When the requesting pod is MISSING from the listed members (its
        # ADDED event has not landed — the union case), its labels shape
        # the context (allow-multislice), so such evaluations get their
        # own memo slot: another member sorting against the same state
        # must not be served a context computed without its labels.  The
        # union is computed ONCE here and passed down.
        members = self._union_requesting_pod(
            self._gang_members(namespace, gang_id, reader=reader, state=state),
            pod)
        pod_key = None
        if pod is not None and members and members[-1] is pod:
            pmd = pod["metadata"]
            pod_key = (pmd.get("namespace", "default"), pmd["name"])
        memo_key = (namespace, gang_id, size, k, wanted_gen,
                    id(reader) if reader is not None else None, pod_key)
        if memo_key in memo:
            self.metrics.inc("gang_ctx_memo_hits")
            return memo[memo_key]
        result = self._reuse_gang_plan(state, gang, k, wanted_gen, reader)
        if result is None:
            result = self._gang_context_uncached(
                state, gang, k, wanted_gen, members=members)
            if result is not None:
                self._store_gang_plan(gang, k, wanted_gen, result)
        memo[memo_key] = result
        return result

    def _store_gang_plan(self, gang: tuple[str, str, int], k: int,
                         wanted_gen: str | None, ctx: dict) -> None:
        ns, gid, _ = gang
        with self._cache_lock:
            # Pop-then-insert refreshes the dict position (LRU-ish):
            # eviction below drops the longest-unrefreshed gang, not the
            # most active one.  The whole sequence holds the lock —
            # concurrent sorts interleaving the pop and the insert was
            # exactly the lost-update window the lockset rule flagged.
            self._gang_plan_cache.pop((ns, gid), None)
            self._gang_plan_cache[(ns, gid)] = {
                "k": k, "gen": wanted_gen,
                # Full remaining plan at plan time; reuse filters out
                # nodes that bind since consumed, so no per-bind cache
                # surgery.
                "plan": dict(ctx["plan"]), "order": list(ctx["order"]),
            }
            while len(self._gang_plan_cache) > self._GANG_PLAN_CACHE_MAX:
                self._gang_plan_cache.pop(next(iter(self._gang_plan_cache)))

    def _reuse_gang_plan(self, state: ClusterState,
                         gang: tuple[str, str, int], k: int,
                         wanted_gen: str | None, reader=None) -> dict | None:
        """Validate-and-reuse a previously computed gang plan against the
        CURRENT state: every not-yet-bound planned member's chips must still
        be free, and every bound member must sit on a planned node.  Listing
        members is cheap (informer mirror / in-memory fake); what this
        skips is the planning search itself."""
        ns, gid, size = gang
        with self._cache_lock:
            # The entry value is replaced wholesale on store (never
            # mutated in place), so holding the lock for the lookup
            # alone hands back a consistent snapshot.
            cached = self._gang_plan_cache.get((ns, gid))
        if cached is None or cached["k"] != k or cached["gen"] != wanted_gen:
            return None
        members = self._gang_members(ns, gid, reader=reader, state=state)
        bound_nodes = {p["spec"]["nodeName"] for p in members
                       if p["spec"].get("nodeName")}
        remaining = size - sum(1 for p in members if p["spec"].get("nodeName"))
        if remaining <= 0:
            with self._cache_lock:
                self._gang_plan_cache.pop((ns, gid), None)  # fully bound
            return None
        rem_nodes = [n for n in cached["order"] if n not in bound_nodes]
        # Length equation doubles as the off-plan check: the cached order
        # held (size - bound-at-plan-time) nodes, so the counts only agree
        # when every member bound since then consumed exactly one planned
        # node.  A member on an unplanned node (or two sharing one) breaks
        # the equality -> full replan.
        if len(rem_nodes) != remaining:
            return None
        for n in rem_nodes:
            free = set(state.free_chips_on_node(n))
            if not set(cached["plan"][n].chips) <= free:
                return None  # someone took planned chips — replan
        self.metrics.inc("gang_plan_reuse_hits")
        return {"plan": {n: cached["plan"][n] for n in rem_nodes},
                "order": rem_nodes,
                "stats": {"plan_reused": True}}

    def _gang_context_uncached(self, state: ClusterState,
                               gang: tuple[str, str, int], k: int,
                               wanted_gen: str | None = None,
                               members: list[dict] | None = None) -> dict | None:
        namespace, gang_id, size = gang
        if members is None:
            members = self._gang_members(namespace, gang_id, state=state)
        bound = [p for p in members if p["spec"].get("nodeName")]
        remaining = size - len(bound)
        if remaining <= 0:
            return None
        allow_multi = self._gang_allows_multislice(members)
        dom_ids = {d.slice_id for p in bound
                   if (d := state.domain_of_node(p["spec"]["nodeName"])) is not None}
        if len(dom_ids) > 1 and not allow_multi:
            # Members already straddle ICI domains — such a gang can never
            # be contiguous; refuse to extend it (its assumptions will age
            # out via the GC).
            return None
        exclude = {p["spec"]["nodeName"] for p in bound}
        # One frozen copy serves every per-domain memo key below (the
        # screen and the candidate-map memo key both need the hashable
        # form; building it per probe was measurable at 4096 nodes).
        exclude_fs = frozenset(exclude)
        all_doms = sorted(state.domains.values(), key=lambda d: d.slice_id)
        if wanted_gen is not None:
            all_doms = [d for d in all_doms
                        if d.topology.generation.name == wanted_gen]

        def ctx(plans: dict[str, Placement], stats: dict | None = None) -> dict:
            order = sorted(
                plans,
                key=lambda n: ((d := state.domain_of_node(n)).slice_id,
                               d.host_by_node[n]))
            # ``stats``: gang-search observability carried into explain
            # records — plan shape and, for multislice, how much of the
            # composition budget the search consumed.
            return {"plan": plans, "order": order,
                    "stats": stats or {"multislice": False}}

        # Phase 1: one ICI-contiguous domain (the core guarantee).  A gang
        # with members bound in exactly one domain extends that domain; a
        # fresh gang may pick any.
        if len(dom_ids) == 1:
            phase1 = [d for d in all_doms if d.slice_id in dom_ids]
        elif not dom_ids:
            phase1 = all_doms
        else:
            phase1 = []  # already split (multislice in progress)
        if self.VECTOR_GANG_PLAN and phase1:
            # Vectorized necessary-condition screen: drop domains whose
            # >=k-free host count or free volume cannot cover the
            # remaining replicas BEFORE paying their per-host candidate
            # maps.  The screen only over-admits (sound), so the first
            # surviving domain that plans is the same winner the
            # probe-every-domain loop finds — byte-identical plans.
            vol = remaining * k
            kept = []
            for dom in phase1:
                cap = self._vector_cap(state, dom, k, exclude,
                                       exclude_key=exclude_fs)
                if cap is not None and (
                        cap < remaining
                        or dom.allocator.free_count < vol):
                    continue
                kept.append(dom)
            if len(kept) < len(phase1):
                self.metrics.inc("gang_domains_screened",
                                 len(phase1) - len(kept))
            phase1 = kept
        for dom in phase1:
            plan = self._plan_gang(state, dom, remaining, k, exclude,
                                   exclude_key=exclude_fs)
            if plan is not None:
                return ctx(plan)
        if not allow_multi:
            return None
        # Phase 2 (opt-in multislice): split across domains.  Constraints:
        # all sub-gangs share ONE generation even without an explicit pin
        # (quota classing — a DP job must not straddle v4/v5p; a JAX
        # multislice mesh cannot form across generations), and each slice's
        # sub-gang is still a contiguous host box.  Within a generation,
        # candidate splits (compositions of the remaining replica count over
        # the feasible domains) are scored with
        # predict_multidomain_allreduce_gbps and the max-scoring split wins
        # — greedy largest-first can lose, e.g. when draining one large
        # domain to a 1-replica remainder in a second domain scores below
        # two balanced sub-gangs whose narrowest DCN attachment is wider.
        if dom_ids:
            gens = [state.domains[next(iter(dom_ids))].topology.generation.name]
        else:
            gens = sorted({d.topology.generation.name for d in all_doms})
        # Chips of already-bound members participate in the collective and
        # must count toward the split's score.
        bound_by_dom: dict[str, set[Coord]] = {}
        for p in bound:
            bdom = state.domain_of_node(p["spec"]["nodeName"])
            grp = p["metadata"].get("annotations", {}).get(ko.ANN_GROUP)
            if bdom is not None and grp:
                bound_by_dom.setdefault(bdom.slice_id, set()).update(
                    ko.ann_to_coords(grp))
        for gen in gens:
            gen_doms = [d for d in all_doms
                        if d.topology.generation.name == gen]
            cost = self.config.cost_model(gen)
            plan_cache: dict[tuple[str, int], dict[str, Placement] | None] = {}

            def plan_for(dom, m: int):
                key = (dom.slice_id, m)
                if key not in plan_cache:
                    plan_cache[key] = self._plan_gang(
                        state, dom, m, k, exclude, exclude_key=exclude_fs)
                return plan_cache[key]

            def max_feasible(dom) -> int:
                hi = min(remaining, len(dom.node_by_host))
                if self.VECTOR_GANG_PLAN:
                    # Screened upper bound: no domain can seat more
                    # members than its >=k-free host count or its free
                    # volume allows, so the probe starts there instead
                    # of at the host count — same answer, fewer probes.
                    cap = self._vector_cap(state, dom, k, exclude,
                                           exclude_key=exclude_fs)
                    if cap is not None:
                        hi = min(hi, cap, dom.allocator.free_count // k)
                for m in range(hi, 0, -1):
                    if plan_for(dom, m) is not None:
                        return m
                return 0

            capacity = {d.slice_id: max_feasible(d) for d in gen_doms}
            doms = [d for d in gen_doms if capacity[d.slice_id] > 0]
            if sum(capacity[d.slice_id] for d in doms) < remaining:
                continue
            best_key: tuple | None = None
            best_plans: dict[str, Placement] | None = None

            def consider(split: list[tuple]) -> None:
                nonlocal best_key, best_plans
                plans: dict[str, Placement] = {}
                chips_by_dom: dict[str, set[Coord]] = {
                    sid: set(cs) for sid, cs in bound_by_dom.items()}
                topo_by_dom = {d.slice_id: d.topology for d in gen_doms}
                for dom, m in split:
                    sub = plan_for(dom, m)
                    if sub is None:
                        return
                    plans.update(sub)
                    chips_by_dom.setdefault(dom.slice_id, set()).update(
                        c for p in sub.values() for c in p.chips)
                score = predict_multidomain_allreduce_gbps(
                    [(topo_by_dom[sid], frozenset(cs))
                     for sid, cs in sorted(chips_by_dom.items())
                     if sid in topo_by_dom],
                    cost,
                )
                # Ties: fewer domains (shorter DCN ring), then deterministic.
                key = (-score, len(chips_by_dom),
                       tuple(sorted(sid for sid, _ in
                                    ((d.slice_id, m) for d, m in split))))
                if best_key is None or key < best_key:
                    best_key, best_plans = key, plans

            # Budget bounds the search on pathological states (many domains
            # x large gangs).  Enumeration goes largest-m-first per domain,
            # so the earliest splits visited include the old greedy plan —
            # exhausting the budget degrades to greedy-or-better, never
            # worse.
            budget = [512]

            def compositions(idx: int, rem: int, acc: list[tuple]) -> None:
                if rem == 0:
                    if budget[0] > 0:
                        budget[0] -= 1
                        consider(acc)
                    return
                if idx >= len(doms) or budget[0] <= 0:
                    return
                dom = doms[idx]
                tail_cap = sum(capacity[d.slice_id] for d in doms[idx + 1:])
                lo = max(0, rem - tail_cap)
                for m in range(min(rem, capacity[dom.slice_id]), lo - 1, -1):
                    compositions(idx + 1, rem - m,
                                 acc + ([(dom, m)] if m else []))

            compositions(0, remaining, [])
            # Observability for the budget (scale bench): how much of the
            # 512-composition search this gang actually consumed.
            self.metrics.inc("gang_multislice_compositions_considered",
                             512 - budget[0])
            if best_plans is not None:
                self.metrics.inc("gang_multislice_plans")
                return ctx(best_plans, {
                    "multislice": True,
                    "compositions_considered": 512 - budget[0]})
        return None

    def _score_gang_node(self, gang_ctx: dict | None, node_name: str) -> int:
        if gang_ctx is None or node_name not in gang_ctx["plan"]:
            return 0
        # Rank member nodes in (domain, host-grid coordinate) order, NOT
        # node-name order: binding must march through each planned host box
        # compactly so the hosts still free for later members remain a
        # connected region (lexicographic "node-1" < "node-10" < "node-2"
        # ordering fragments the grid mid-gang).
        #
        # Ranks scale into [1, MAX_PRIORITY] across the whole plan instead
        # of clamping at MAX_PRIORITY - rank (which saturated to all-ties
        # past 10 members): rank 0 is always strictly highest, so gangs of
        # any size bind in host-box order under max-score-first selection
        # (each bind re-plans, so only the front of the order must win).
        order = gang_ctx["order"]
        n = len(order)
        if n <= 1:
            return MAX_PRIORITY
        rank = order.index(node_name)
        if rank == 0:
            return MAX_PRIORITY
        # ceil keeps every rank > 0 strictly below MAX_PRIORITY at any gang
        # size (round() re-ties rank 1 with rank 0 from n=19 up).
        return max(1, MAX_PRIORITY - math.ceil(rank * (MAX_PRIORITY - 1)
                                               / (n - 1)))

    def _release_gang_assumptions(self, namespace: str, gang_id: str,
                                  members: list[dict] | None = None) -> list[str]:
        """Clear the scheduling annotations of a gang's bound-but-unconfirmed
        members — the same wipe the TTL GC would eventually do (gc.py), done
        at the moment the gang is known infeasible.  Confirmed members have
        running containers; reclaiming those is the job controller's call,
        exactly as the GC's stranded-gang rule says.  The CAS guard covers
        a ``members`` list a few milliseconds stale (the caller just listed
        it): a pod that changed meanwhile Conflicts and is left to the GC."""
        released = []
        for p in members if members is not None else self._gang_members(
                namespace, gang_id):
            md = p["metadata"]
            anns = md.get("annotations", {})
            if not anns.get(ko.ANN_GROUP) or anns.get(ko.ANN_ASSIGNED) != "false":
                continue
            if ExtenderScheduler.BIND_ANN_TEMPLATE:
                wipe: dict = dict(self._wipe_ann_tmpl)
                if ko.ANN_BOUND_BY in anns:
                    wipe[ko.ANN_BOUND_BY] = None
            else:
                wipe = {ko.ANN_GROUP: None, ko.ANN_ASSUME_TIME: None,
                        ko.ANN_ASSIGNED: None, ko.ANN_PREDICTED_GBPS: None}
                if self.config.replica_id or ko.ANN_BOUND_BY in anns:
                    # Replicated deployments stamp the binding replica's
                    # id; a release must clear it with the claim (a peer's
                    # wiped gang must not read as still-owned).
                    # Conditional so the single-scheduler patch stream
                    # stays byte-identical.
                    wipe[ko.ANN_BOUND_BY] = None
            try:
                self._api_call(
                    "release", self.api.patch_annotations,
                    "pods", md["name"], wipe,
                    namespace=md.get("namespace", "default"),
                    expect_version=md.get("resourceVersion"),
                )
            except NotFound:
                continue  # deleted meanwhile — nothing left to release
            except Conflict:
                # Either a racing writer (Allocate confirm — leave it to
                # the GC) or the echo of our OWN release: an ambiguous
                # timeout after the patch applied means the retry replays
                # against a bumped resourceVersion and conflicts with its
                # own success.  Re-read and reconcile, as the bind leg
                # does: assumptions already wiped = the release landed.
                try:
                    cur = self.api.get("pods", md["name"],
                                       md.get("namespace", "default"))
                except NotFound:
                    continue
                except ApiUnavailable:
                    self.metrics.inc("release_unavailable")
                    continue
                if (cur.get("metadata", {}).get("annotations")
                        or {}).get(ko.ANN_GROUP):
                    continue  # genuine racing writer — leave it to the GC
                self.metrics.inc("release_conflict_resolved")
            except ApiUnavailable:
                # Retries exhausted: the TTL GC is the durable backstop for
                # exactly this — an assumption we could not release now.
                self.metrics.inc("release_unavailable")
                continue
            released.append(md["name"])
            if self.informer is not None:
                try:
                    self.informer.observe(
                        "pods", self.api.get("pods", md["name"],
                                             md.get("namespace", "default")))
                except (NotFound, ApiUnavailable):
                    pass  # watch delivers the authoritative event shortly
        if released:
            self.metrics.inc("gang_assumptions_released", len(released))
            # The derived state still counts those chips as used.
            with self._cache_lock:
                self._cached_state = None
        return released

    # ---- priority (tputopo.priority) ---------------------------------------

    @staticmethod
    def admission_order(pods: list[dict]) -> list[dict]:
        """Pending pods in tier-aware admission order: high-priority
        gangs sort before lower tiers, FIFO within a tier
        (tputopo.priority.tiers — served at ``GET /debug/pending``; the
        sim's scheduling wake applies the same tier-then-FIFO rule at
        the job level)."""
        # Lazy import: tputopo.priority.preempt imports this module.
        from tputopo.priority.tiers import admission_order as _order

        return _order(pods)

    def plan_preempt(self, replicas: int, k: int,
                     priority: int):
        """Dry-run targeted-preemption plan for a pending
        ``replicas x k``-chip demand at ``priority``: the cheapest
        strictly-lower-tier eviction set that would let it place, or
        None (served by ``GET /debug/preempt``; executing the evictions
        is the job controller's call, exactly like /debug/defrag).

        When any bound pod carries checkpoint annotations the victims
        are priced by :func:`tputopo.elastic.ckpt.victim_costs` — the
        same arithmetic the sim engine's tier tally charges, fixing the
        drift where this dry-run's explain priced victims in
        whole-runtime seconds while the report counted lost *virtual*
        work."""
        from tputopo.defrag.planner import list_pods_nocopy
        from tputopo.priority.preempt import plan_preemption

        self.metrics.inc("preempt_plans_considered")
        informer_reader = (self.informer if self.informer is not None
                           and self.informer.synced else None)
        state = self._state(allow_cache=True, reader=informer_reader)
        pods = list_pods_nocopy(informer_reader if informer_reader
                                is not None else self.api)
        plan = plan_preemption(
            state, (replicas, k), priority, pods,
            max_moves=self.config.preempt_max_moves,
            max_chips_moved=self.config.preempt_max_chips_moved,
            cost_of=self._ckpt_cost_of(pods))
        if plan is not None:
            self.metrics.inc("preempt_plans_found")
        return plan

    # ---- elastic migration (tputopo.elastic) -------------------------------

    def _ckpt_cost_of(self, pods):
        """Checkpoint-aware victim pricing closure for the dry-run
        planners, or None when no bound pod carries checkpoint
        annotations — a pre-elastic fleet keeps the raw chip-volume
        ranking, so every existing plan byte is pinned.  Unknown victim
        keys fail closed (effectively infinite cost, full volume): a
        pod listing racing a delete must never make a victim look
        free.  The 1e18 sentinel matters — ``float('inf')`` would leak
        ``Infinity`` into a chosen plan's describe(), which is not
        valid strict JSON."""
        from tputopo.elastic.ckpt import victim_costs

        if not any(ko.ANN_CKPT_PERIOD in (p.get("metadata", {})
                                          .get("annotations") or {})
                   for p in pods):
            return None
        costs = victim_costs(pods, self.clock())

        def cost_of(key: str, chips_held: int) -> tuple[float, float]:
            got = costs.get(key)
            if got is None:
                return 1e18, float(chips_held)
            return got

        return cost_of

    def plan_migrate(self, gang: str, namespace: str = "default"):
        """Dry-run migration plan for a BOUND gang (served at
        ``GET /debug/migrate?gang=...``): what evicting it right now
        would destroy (checkpoint-charged, the same
        :func:`tputopo.elastic.ckpt.victim_costs` arithmetic the sim
        tier tally uses) and whether a destination domain currently
        holds enough per-host free boxes to land it
        (:func:`tputopo.elastic.migrate.plan_destination` — the same
        necessary-condition screen the sim engine runs before it
        upgrades an eviction to a migration).  Read-only; returns None
        when no bound pod matches the gang."""
        from tputopo.defrag.planner import list_pods_nocopy
        from tputopo.elastic.ckpt import victim_costs
        from tputopo.elastic.migrate import plan_destination

        self.metrics.inc("migrate_plans_considered")
        informer_reader = (self.informer if self.informer is not None
                           and self.informer.synced else None)
        state = self._state(allow_cache=True, reader=informer_reader)
        pods = list_pods_nocopy(informer_reader if informer_reader
                                is not None else self.api)
        members = []
        for p in pods:
            md = p.get("metadata", {})
            if md.get("namespace", "default") != namespace:
                continue
            if not p.get("spec", {}).get("nodeName"):
                continue
            anns = md.get("annotations") or {}
            if anns.get(ko.ANN_GANG_ID) == gang or md.get("name") == gang:
                members.append(p)
        if not members:
            return None
        replicas = len(members)
        k = max(ko.pod_requested_chips(p) for p in members)
        key = f"{namespace}/{gang}"
        charged, destroyed = victim_costs(pods, self.clock()).get(
            key, (0.0, 0.0))
        nodes = {p["spec"]["nodeName"] for p in members}
        current = sorted(sid for sid, dom in state.domains.items()
                         if nodes & dom.node_masks.keys())
        dest = plan_destination(
            replicas, k,
            ((sid, state.domains[sid].allocator,
              state.domains[sid].node_masks)
             for sid in sorted(state.domains)))
        if dest is not None:
            self.metrics.inc("migrate_plans_found")
        return {
            "gang": key,
            "replicas": replicas,
            "chips_per_member": k,
            "current_domains": current,
            "cost": {"charged_cost_s": round(charged, 6),
                     "destroyed_chips": round(destroyed, 6)},
            "destination": dest,
        }

    # ---- joint batch admission (tputopo.batch) -----------------------------

    def plan_batch(self, window: int = 4):
        """Dry-run joint batch-admission plan for the CURRENT pending
        queue (served at ``GET /debug/batchplan``): every unbound pod,
        taken in :meth:`admission_order` and grouped into gangs, solved
        jointly by :func:`tputopo.batch.plan_batch` over this
        scheduler's score index.  Read-only — executing the plan stays
        the scheduling loop's call, exactly like /debug/preempt."""
        from tputopo.batch import GangRequest
        from tputopo.batch import plan_batch as _plan_batch
        from tputopo.defrag.planner import list_pods_nocopy

        self.metrics.inc("batch_plans_considered")
        informer_reader = (self.informer if self.informer is not None
                           and self.informer.synced else None)
        state = self._state(allow_cache=True, reader=informer_reader)
        pods = list_pods_nocopy(informer_reader if informer_reader
                                is not None else self.api)
        pending = [p for p in pods
                   if not p.get("spec", {}).get("nodeName")]
        gangs: list[GangRequest] = []
        seen_gangs: set[tuple[str, str]] = set()
        for p in self.admission_order(pending):
            k = ko.pod_requested_chips(p)
            if k <= 0:
                continue
            md = p.get("metadata", {})
            g = _gang_of(p)
            if g is not None:
                if (g[0], g[1]) in seen_gangs:
                    continue  # one GangRequest per gang, first-seen order
                seen_gangs.add((g[0], g[1]))
                name, replicas = g[1], int(g[2])
            else:
                name, replicas = md.get("name", ""), 1
            meta = {**md.get("annotations", {}), **md.get("labels", {})}
            gangs.append(GangRequest(
                len(gangs), name, replicas, k,
                priority=ko.pod_priority(p),
                multislice=meta.get(LABEL_ALLOW_MULTISLICE) == "true"))
        node_names = sorted(state._dom_by_node)
        memo: dict[int, tuple[dict[str, int], tuple | None]] = {}

        def scorer(k: int, key: str | None = None):
            got = memo.get(k)
            if got is None:
                got = memo[k] = self.batch_scores(k, node_names)
            return got

        dom_nodes: dict[str, list[str]] = {}
        for n in node_names:
            dom_nodes.setdefault(state.domain_of_node(n).slice_id,
                                 []).append(n)
        plan = _plan_batch(
            gangs, scorer, dom_nodes,
            {dom.slice_id: dom.allocator.free_count
             for dom in state.domains.values()},
            window=window)
        if plan.order:
            self.metrics.inc("batch_plans_planned")
        return plan

    # ---- crash recovery ----------------------------------------------------

    # thread-root: startup/crash recovery runs while the informer watch threads are already live (the chaos-injected crash-restart path re-enters here)
    def recover(self) -> dict:
        """Startup/crash recovery: rebuild the assumption cache from API
        truth and resolve every **in-flight gang** atomically.

        The reference's statelessness posture (SURVEY.md §5.4: "a
        restarted extender rebuilds its world from the API server") covers
        occupancy but not in-flight *work*: an extender killed mid-gang-
        bind leaves a gang with some members bound-and-assumed and the
        rest Pending — chips half-reserved, the gang unable to run.  This
        method closes that gap with the all-or-nothing rule applied at
        restart: each such gang is either **completed** (the remaining
        members still plan and bind — the normal sort/bind pipeline, so
        recovery exercises no special-case placement code) or **released**
        (every unconfirmed member's assumptions wiped via the CAS-guarded
        release; the job controller re-queues it) — never left half.
        Gangs with *confirmed* members that cannot complete are
        additionally flagged ``stranded`` (running containers are the job
        controller's to reclaim, the GC's stranded-gang rule).

        Returns ``{"completed": [...], "released": [...], "stranded":
        [...]}`` of ``namespace/gang-id`` strings, for logs and tests."""
        self.metrics.inc("crash_recoveries")
        with self._cache_lock:
            self._cached_state = None
            self._cached_informer_version = None
            self._gang_plan_cache.clear()
        with self._bind_lock:
            self._unmirrored_binds.clear()
        outcome: dict = {"completed": [], "released": [], "stranded": []}
        state = self._state(allow_cache=False)
        node_names = sorted(state._dom_by_node)
        try:
            pods = self._api_call("list", self.api.list, "pods")
        except ApiUnavailable as e:
            outcome["error"] = f"api unavailable listing pods: {e}"
            return outcome
        gangs: dict[tuple[str, str], dict] = {}
        for p in pods:
            g = _gang_of(p)
            if g is None:
                continue
            info = gangs.setdefault((g[0], g[1]),
                                    {"size": g[2], "members": []})
            info["members"].append(p)
        for (ns, gid), info in sorted(gangs.items()):
            members = info["members"]
            bound = [p for p in members if p["spec"].get("nodeName")]
            if not bound or len(bound) >= info["size"]:
                continue  # whole or untouched — not in flight
            # Replicated control plane: an in-flight gang whose bound
            # members were committed by a DIFFERENT replica is still ours
            # to reconcile — completing it ADOPTS the peer's binds (the
            # all-or-nothing rule is cluster-wide, not per-replica).
            foreign = self.config.replica_id and any(
                (p["metadata"].get("annotations", {}) or {})
                .get(ko.ANN_BOUND_BY)
                not in (None, "", self.config.replica_id)
                for p in bound)
            # Completing requires the full roster: with a member pod
            # absent (deleted, or not yet recreated by the job
            # controller), binding everything that EXISTS would still
            # leave the gang partially bound — short rosters go straight
            # to release.
            completed = len(members) >= info["size"]
            for p in sorted((m for m in members
                             if not m["spec"].get("nodeName")),
                            key=lambda m: m["metadata"]["name"]) \
                    if completed else ():
                try:
                    scores = self.sort(p, node_names)
                    best = (max(scores, key=lambda s: (s["Score"], s["Host"]))
                            if scores else None)
                    if best is None or best["Score"] <= 0:
                        completed = False
                        break
                    self.bind(p["metadata"]["name"], ns, best["Host"])
                except BindError:
                    completed = False
                    break
            if completed:
                self.metrics.inc("crash_gangs_completed")
                if foreign:
                    self.metrics.inc("recover_foreign_bind_adopted")
                outcome["completed"].append(f"{ns}/{gid}")
                continue
            # Release-or-complete, never half: wipe every still-unconfirmed
            # member (bind's infeasible path may already have — the wipe is
            # idempotent); confirmed members are running and flagged.
            members_now = self._gang_members(ns, gid)
            self._release_gang_assumptions(ns, gid, members=members_now)
            self.metrics.inc("crash_gangs_released")
            outcome["released"].append(f"{ns}/{gid}")
            if any(p["spec"].get("nodeName")
                   and p["metadata"].get("annotations", {})
                         .get(ko.ANN_ASSIGNED) == "true"
                   for p in members_now):
                outcome["stranded"].append(f"{ns}/{gid}")
        return outcome

    # ---- retried API calls -------------------------------------------------

    #: Per-verb retry deadlines (seconds on the scheduler clock): reads
    #: give up fast (the caller re-queues), the CAS write leg gets the
    #: longest leash (abandoning it mid-gang costs a rollback).
    _VERB_DEADLINE_S = {"get": 5.0, "cas": 10.0, "release": 5.0,
                        "list": 10.0}

    def _api_call(self, verb: str, fn, *args, **kwargs):
        """One API call under the shared RetryPolicy.  Each retry is
        counted by failure class (``retry_api_timeout`` /
        ``retry_api_unavailable``) so a chaos run's recovery work is
        attributable from /metrics and the sim's chaos block."""
        return self._retry_call(
            fn, *args, deadline_s=self._VERB_DEADLINE_S.get(verb), **kwargs)

    # ---- bind --------------------------------------------------------------

    def _replay_decision(self, pod: dict, node_name: str) -> dict:
        """Reconstruct the recorded decision of an already-bound pod — the
        idempotent answer to a retried bind (ADVICE r3: a kube-scheduler
        retry after a timed-out-but-successful bind must not surface a
        spurious failure for a correctly placed pod)."""
        md = pod["metadata"]
        anns = md.get("annotations", {})
        chips = ko.ann_to_coords(anns.get(ko.ANN_GROUP, ""))
        informer_reader = (self.informer if self.informer is not None
                           and self.informer.synced else None)
        state = self._state(allow_cache=True, reader=informer_reader)
        dom = state.domain_of_node(node_name)
        contiguous = True
        if dom is not None and len(chips) > 1:
            contiguous = _box_of(dom.topology, frozenset(chips)) is not None
        try:
            gbps = float(anns.get(ko.ANN_PREDICTED_GBPS, "0"))
        except (TypeError, ValueError):
            gbps = 0.0
        return {
            "pod": f"{md.get('namespace', 'default')}/{md['name']}",
            "node": node_name,
            "slice": dom.slice_id if dom is not None else None,
            "chips": [list(c) for c in chips],
            "contiguous": contiguous,
            "predicted_allreduce_gbps": gbps,
            "gang": anns.get(ko.ANN_GANG_ID),
            "time": _assume_time_of(pod),
            "replayed": True,
        }

    def bind(self, pod_name: str, namespace: str, node_name: str) -> dict:
        """The bind verb (design.md:119, 223-234): re-run selection on the
        winning node, stamp the assignment handshake, bind the pod."""
        with self._bind_lock:
            return self._bind_locked(pod_name, namespace, node_name)

    # The holds-lock claims on the two helpers below are CHECKED by the
    # lockset rule at every call site, not trusted: bind() above is the
    # one caller and takes the lock first.

    def _repair_write_through(self) -> None:  # holds-lock: _bind_lock
        """Re-attempt the mirror write-through of binds whose read-back
        failed.  Success (or the pod being gone) closes the gap; anything
        still open keeps binds on the authoritative sync path.  Called
        under the bind lock."""
        for key in list(self._unmirrored_binds):
            ns, name = key
            try:
                self.informer.observe("pods", self.api.get("pods", name, ns))
            except NotFound:
                pass  # deleted — its assignment no longer exists anywhere
            except ApiUnavailable:
                continue  # still unreachable; stay authoritative
            self._unmirrored_binds.discard(key)
            self.metrics.inc("bind_write_through_repaired")

    def _bind_delta_state(self, state: ClusterState, pod_name: str,
                          namespace: str, node_name: str, placement,
                          now: float, gang_id: str | None):
        """``state`` plus this just-committed bind applied (the O(chips)
        copy-on-write delta both cache modes publish), or None when the
        delta cannot apply and the caller must drop the derived state."""
        try:
            return state.with_bind(PodAssignment(
                pod_name=pod_name,
                namespace=namespace or "default",
                node_name=node_name,
                chips=list(placement.chips),
                assigned=False, assume_time=now,
                gang_id=gang_id))
        except ValueError:
            return None

    def _resolve_bind_conflict(self, pod_name: str, namespace: str,
                               node_name: str, anns: dict) -> dict | None:
        """After a Conflict from the bind subresource: the pod as-bound if
        the conflict is the echo of our own (timed-out-but-applied) bind —
        same node, same chip group — else None (a real race)."""
        try:
            cur = self._api_call("get", self.api.get, "pods", pod_name,
                                 namespace)
        except (NotFound, ApiUnavailable):
            return None
        if bound_as_planned(cur, node_name, anns[ko.ANN_GROUP]):
            self.metrics.inc("bind_ambiguous_recovered")
            return cur
        return None

    # ---- replicated-control-plane arbitration (shared_writers) -------------

    def _own_claim_landed(self, pod_name: str, namespace: str,
                          anns: dict) -> bool:
        """After a Conflict on the CAS-guarded claim patch: True when the
        pod already carries OUR exact claim (group + assume-time) — the
        echo of an applied-then-timed-out patch replaying against its own
        resourceVersion bump.  False on any read failure or a foreign
        claim: the caller treats it as a genuine race."""
        try:
            cur = self._api_call("get", self.api.get, "pods", pod_name,
                                 namespace)
        except (NotFound, ApiUnavailable):
            return False
        cur_anns = cur.get("metadata", {}).get("annotations", {}) or {}
        return (cur_anns.get(ko.ANN_GROUP) == anns[ko.ANN_GROUP]
                and cur_anns.get(ko.ANN_ASSUME_TIME)
                == anns[ko.ANN_ASSUME_TIME])

    def _classify_conflict(self, pod_name: str, namespace: str,
                           now: float) -> str:
        """The structured cause of a bind Conflict under shared writers —
        re-read the pod and judge what survives: a claim stamped strictly
        BEFORE ``now`` existed when we planned, so our view was stale
        (``stale_cache``); a same-instant (or unreadable-timestamp)
        surviving claim is a genuinely concurrent race we lost
        (``lost_race``); an unreachable re-read OR no surviving claim at
        all (the conflicting write applied nothing — an injected/spurious
        CAS 409, or the racer's claim was already wiped) leaves nothing
        to arbitrate against (``ambiguous_timeout``; the retry decides).
        Each cause is counted (replica_* counters)."""
        try:
            cur = self._api_call("get", self.api.get, "pods", pod_name,
                                 namespace)
        except (NotFound, ApiUnavailable):
            self.metrics.inc("replica_conflict_ambiguous")
            return "ambiguous_timeout"
        cur_anns = cur.get("metadata", {}).get("annotations", {}) or {}
        claimed = bool(cur.get("spec", {}).get("nodeName")
                       or cur_anns.get(ko.ANN_GROUP))
        if not claimed:
            # Nothing survived the conflicting write: not a race anyone
            # won — calling it lost_race would pollute the taxonomy with
            # phantom peers (the chaos layer injects exactly this shape).
            self.metrics.inc("replica_conflict_ambiguous")
            return "ambiguous_timeout"
        claim_t = None
        try:
            claim_t = float(cur_anns.get(ko.ANN_ASSUME_TIME, ""))
        except (TypeError, ValueError):
            claim_t = None
        if claim_t is not None and math.isfinite(claim_t) and claim_t < now:
            self.metrics.inc("replica_stale_cache_aborts")
            return "stale_cache"
        self.metrics.inc("replica_bind_lost_race")
        return "lost_race"

    def _list_claims(self, node_name: str, now: float) -> list[tuple]:
        """Live chip claims on ``node_name`` as ``(assume_time, namespace,
        pod_name, chip_set)`` tuples — the claim check's arbitration
        universe.  A pod's chips must live on its node, so cross-pod
        overlap is only possible between same-node claims.  Expired
        unconfirmed assumptions are excluded by the same TTL judgement
        sync() applies: their chips are NOT occupancy, and retreating
        before a corpse the GC will wipe would stall placements a
        single-scheduler deployment happily makes."""
        out = []
        for pod in self._list_claims_raw():
            pa = _pod_assignment_of(pod)
            if pa is None or pa.node_name != node_name:
                continue
            if not pa.assigned and \
                    now - pa.assume_time > self.config.assume_ttl_s:
                continue  # expired — not occupancy (sync's rule)
            out.append((pa.assume_time, pa.namespace, pa.pod_name,
                        {tuple(c) for c in pa.chips}))
        return out

    def _claim_check(self, pod_name: str, namespace: str, node_name: str,  # holds-lock: _bind_lock
                     placement, now: float, tr) -> None:
        """Post-commit chip-claim arbitration (shared_writers): raise a
        classified ``conflict`` BindError — after retreating — when ANY
        other live claim overlaps this bind's chips.  Why "any", with no
        tie-break: at this check, an overlapping claim either committed
        BEFORE ours (its own post-commit check has already run against a
        world without our claim and passed — it keeps the chips; only we
        can still retreat) or is concurrently in flight (each racer sees
        the other and both retreat — wasteful but safe, and the jittered
        retry re-plans from fresh truth).  A tie-break that ever lets the
        LATER committer keep its claim would double-book: the earlier
        winner has already stopped checking.  Cause: an overlapping
        claim stamped strictly before ``now`` was knowable when we
        planned (``stale_cache``); a same-instant claim is a genuinely
        concurrent race (``lost_race``).  An unreadable claim universe
        retreats conservatively (``ambiguous_timeout``) — a possibly-
        double-booked chip must never survive on a read error."""
        ns = namespace or "default"
        mine = {tuple(c) for c in placement.chips}
        winner = None
        cause = None
        try:
            claims = self._list_claims(node_name, now)
        except (ApiUnavailable, ApiTimeout):
            cause = "ambiguous_timeout"
            self.metrics.inc("replica_conflict_ambiguous")
        if cause is None:
            # Classify against the OLDEST overlapping claim (min by the
            # (assume_time, ns, name) attribution order sync() uses):
            # list_assignments returns (ns, name) order, and breaking on
            # the first hit could report lost_race while an older claim
            # proves the plan stale.
            overlapping = [
                (t, cns, cname, sorted(mine & chips))
                for t, cns, cname, chips in claims
                if (cns, cname) != (ns, pod_name) and mine & chips]
            if not overlapping:
                return  # claim holds
            winner = min(overlapping)
            if winner[0] < now:
                cause = "stale_cache"
                self.metrics.inc("replica_stale_cache_aborts")
            else:
                cause = "lost_race"
                self.metrics.inc("replica_bind_lost_race")
        # Retreat: wipe our own annotations so the chips are free again
        # the moment any peer re-reads.  The pod itself stays bound-but-
        # unclaimed — un-binding is the job controller's delete/recreate
        # (the sim engine's reset path); the TTL GC backstops a failed
        # wipe exactly like any other stale assumption.
        if ExtenderScheduler.BIND_ANN_TEMPLATE:
            wipe: dict = dict(self._wipe_ann_tmpl)
        else:
            wipe = {ko.ANN_GROUP: None, ko.ANN_ASSUME_TIME: None,
                    ko.ANN_ASSIGNED: None, ko.ANN_PREDICTED_GBPS: None}
            if self.config.replica_id:
                wipe[ko.ANN_BOUND_BY] = None
        try:
            self._api_call("release", self.api.patch_annotations, "pods",
                           pod_name, wipe, namespace=ns)
        except (NotFound, Conflict, ApiUnavailable):
            self.metrics.inc("release_unavailable")
        with self._cache_lock:
            self._cached_state = None  # the view that planned this is wrong
        self.metrics.inc("bind_errors")
        self.metrics.inc("bind_conflicts")
        if tr.enabled:
            rec: dict = {"verb": "bind", "pod": f"{ns}/{pod_name}",
                         "node": node_name,
                         "conflict": {"cause": cause, "leg": "claim"}}
            if winner is not None:
                rec["conflict"]["winner"] = f"{winner[1]}/{winner[2]}"
                rec["conflict"]["chips"] = [list(c) for c in winner[3]]
            tr.explain(rec)
        detail = (f"claim on {node_name} lost to {winner[1]}/{winner[2]} "
                  f"(overlap {winner[3]})" if winner is not None
                  else f"claim on {node_name} unverifiable")
        raise BindError(f"bind race on {pod_name}: {detail}",
                        reason="conflict", cause=cause)

    def _bind_locked(self, pod_name: str, namespace: str, node_name: str) -> dict:  # holds-lock: _bind_lock
        tr = self.tracer.start(
            "bind", pod=f"{namespace or 'default'}/{pod_name}",
            node=node_name)
        # The trace context records the finished trace on BOTH exits: a
        # BindError's trace carries the structured failure reason.
        with tr:
            return self._bind_spanned(pod_name, namespace, node_name, tr)

    def _bind_spanned(self, pod_name: str, namespace: str, node_name: str,  # holds-lock: _bind_lock
                      tr) -> dict:
        t0 = self._wall()
        self.metrics.inc("bind_requests")
        memo_base = self._memo_counter_snapshot() if tr.enabled else None
        try:
            pod = self._api_call("get", self.api.get, "pods", pod_name,
                                 namespace)
        except NotFound:
            self.metrics.inc("bind_errors")
            raise BindError(f"pod {namespace}/{pod_name} not found",
                            reason="not_found") from None
        except ApiUnavailable as e:
            # Retries exhausted: fail the verb cleanly — the kube-scheduler
            # (or the sim engine) re-queues the pod and tries again later.
            self.metrics.inc("bind_errors")
            self.metrics.inc("bind_unavailable")
            raise BindError(
                f"api unavailable fetching {namespace}/{pod_name}: {e}",
                reason=("timeout" if isinstance(e, ApiTimeout)
                        else "unavailable")) from e
        # Idempotent retry (ADVICE r3): a bind replayed after a timed-out-
        # but-successful earlier bind must return the recorded decision,
        # not re-place the pod — re-running selection would overwrite the
        # GROUP annotation with different chips while the kubelet may
        # already be allocating the original group.
        prior_node = pod["spec"].get("nodeName")
        if prior_node:
            anns0 = pod["metadata"].get("annotations", {})
            if prior_node == node_name and anns0.get(ko.ANN_GROUP):
                self.metrics.inc("bind_idempotent_replays")
                return self._replay_decision(pod, node_name)
            self.metrics.inc("bind_errors")
            raise BindError(
                f"pod {namespace}/{pod_name} is already bound to "
                f"{prior_node}" + ("" if prior_node == node_name
                                   else f", not {node_name}"),
                reason="already_bound")
        # Sort's informer-coherent derived state serves bind too: binds are
        # serialized, every bind write-throughs its own delta (below), and
        # the API server's CAS on the patch/bind leg stays the authority —
        # so bind no longer pays a full O(pods) cluster re-sync per call
        # (VERDICT r3 #1).  Without an informer — or while any earlier
        # bind's write-through is unrepaired (mirror may lack a committed
        # placement) — sync authoritatively.
        with tr.phase("state") as sp:
            informer_reader = (self.informer if self.informer is not None
                               and self.informer.synced else None)
            if informer_reader is not None and self._unmirrored_binds:
                self._repair_write_through()
            if informer_reader is not None and not self._unmirrored_binds:
                state = self._state(allow_cache=True,
                                    reader=informer_reader, span=sp)
                state_token = self._cached_informer_version
            else:
                # bind_from_cache (ExtenderConfig): informer-less
                # single-writer deployments (the sim's virtual-time
                # engine) may plan binds from the cached derived state;
                # the post-bind delta below keeps the cache coherent with
                # this extender's own writes.
                state = self._state(allow_cache=self.config.bind_from_cache,
                                    span=sp)
                state_token = None
        k = ko.pod_requested_chips(pod)
        if k <= 0:
            self.metrics.inc("bind_errors")
            raise BindError(f"pod {pod_name} requests no {self.config.resource_name}")
        dom = state.domain_of_node(node_name)
        if dom is None:
            self.metrics.inc("bind_errors")
            raise BindError(f"node {node_name} is not part of any TPU slice")
        wanted_gen = _wanted_generation(pod)
        if wanted_gen and dom.topology.generation.name != wanted_gen:
            self.metrics.inc("bind_errors")
            raise BindError(
                f"pod pins generation {wanted_gen!r} but node {node_name} "
                f"is {dom.topology.generation.name} (quota classing)")

        gang = _gang_of(pod)
        gang_id = None
        gang_ctx = None
        with tr.phase("plan"):
            if gang is not None:
                gang_id = gang[1]
                gang_ctx = self._gang_context(state, gang, k, wanted_gen,
                                              reader=informer_reader, pod=pod)
                if gang_ctx is None:
                    # None covers two distinct cases that must not share a
                    # remedy: a FULLY BOUND gang (remaining <= 0 — e.g. a
                    # duplicate bind retried after a timed-out-but-successful
                    # bind, or an extra pod wearing the gang label) holds
                    # live, healthy assumptions that wiping would silently
                    # unplace; only a gang that genuinely cannot fit gets
                    # released.
                    members = self._gang_members(gang[0], gang_id, state=state)
                    n_bound = sum(1 for p in members
                                  if p["spec"].get("nodeName"))
                    if gang[2] - n_bound <= 0:
                        self.metrics.inc("bind_gang_already_bound")
                        raise BindError(
                            f"gang {gang_id!r} already has {n_bound} bound "
                            f"members of declared size {gang[2]} — nothing "
                            "left to bind", reason="already_bound"
                        )
                    self.metrics.inc("bind_gang_infeasible")
                    # All-or-nothing, promptly: members that already hold
                    # assumptions would otherwise block their chips for a
                    # full TTL until the GC expires them (VERDICT r2 #5).
                    # Release every still-unconfirmed member now,
                    # CAS-guarded so a racing Allocate confirm always wins.
                    released = self._release_gang_assumptions(
                        gang[0], gang_id, members=members)
                    with self._cache_lock:
                        self._gang_plan_cache.pop((gang[0], gang_id), None)
                    raise BindError(
                        f"gang {gang_id!r} cannot fit ({gang[2]} x {k} "
                        "chips) — binding nothing (all-or-nothing; released "
                        f"{len(released)} unconfirmed member assumption(s))",
                        reason="gang_infeasible"
                    )
                if node_name not in gang_ctx["plan"]:
                    self.metrics.inc("bind_gang_wrong_node")
                    raise BindError(
                        f"node {node_name} is not in gang {gang_id!r}'s plan "
                        f"(planned: {sorted(gang_ctx['plan'])})",
                        reason="wrong_node"
                    )
                placement = gang_ctx["plan"][node_name]
            else:
                node_free_mask = state.free_mask_on_node(node_name)
                placement = dom.allocator.find(k, free_mask=node_free_mask)
                if placement is None:
                    self.metrics.inc("bind_errors")
                    raise BindError(
                        f"no feasible {k}-chip placement on {node_name} "
                        f"({node_free_mask.bit_count()} free)"
                    )

        now = self.clock()
        if ExtenderScheduler.BIND_ANN_TEMPLATE:
            anns = dict(self._bind_ann_tmpl)
            anns[ko.ANN_GROUP] = ko.coords_to_ann(placement.chips)
            anns[ko.ANN_ASSUME_TIME] = str(now)
            anns[ko.ANN_PREDICTED_GBPS] = f"{placement.score_gbps:.3f}"
            if gang_id is not None:
                anns[ko.ANN_GANG_ID] = gang_id
        else:
            anns = {
                ko.ANN_GROUP: ko.coords_to_ann(placement.chips),
                ko.ANN_ASSUME_TIME: str(now),
                ko.ANN_ASSIGNED: "false",
                ko.ANN_PREDICTED_GBPS: f"{placement.score_gbps:.3f}",
            }
            if gang_id is not None:
                anns[ko.ANN_GANG_ID] = gang_id
            if self.config.replica_id:
                # Replica identity on every committed bind (replicated
                # control plane): recover() reads it to tell its own
                # in-flight binds from a peer's.  Absent without a
                # replica_id — the single-scheduler annotation vocabulary
                # is byte-identical.
                anns[ko.ANN_BOUND_BY] = self.config.replica_id
        with tr.phase("cas_patch"):
            try:
                try:
                    # shared_writers: the claim patch is CAS-guarded on
                    # the verb's own read — a peer that patched/bound this
                    # pod meanwhile Conflicts cleanly instead of having
                    # its claim silently overwritten (the overwrite would
                    # leak the peer's chips AND stamp our group onto a
                    # pod bound to the peer's node).  Single-scheduler
                    # mode passes None: byte-identical to the historical
                    # un-guarded patch.
                    self._api_call(
                        "cas", self.api.patch_annotations, "pods",
                        pod_name, anns, namespace,
                        expect_version=(
                            pod["metadata"].get("resourceVersion")
                            if self.config.shared_writers else None))
                except Conflict:
                    # CAS reconciliation: an ambiguous timeout on the
                    # patch leg (applied, then timed out) replays against
                    # its own bumped resourceVersion.  Re-read: our exact
                    # claim present means the patch landed — anything
                    # else is a genuine racing writer.
                    if not self._own_claim_landed(pod_name, namespace, anns):
                        raise
                    self.metrics.inc("bind_ambiguous_recovered")
                try:
                    bound_obj = self._api_call("cas", self.api.bind_pod,
                                               pod_name, node_name, namespace)
                except Conflict as e:
                    # Ambiguity resolution: a retried bind whose earlier
                    # attempt actually committed (timeout-after-apply)
                    # conflicts against its OWN success.  Re-read: bound to
                    # our node carrying our chip group means the bind is
                    # done — anything else is a genuine race.
                    bound_obj = self._resolve_bind_conflict(
                        pod_name, namespace, node_name, anns)
                    if bound_obj is None:
                        raise
            except Conflict as e:
                self.metrics.inc("bind_errors")
                self.metrics.inc("bind_conflicts")
                cause = None
                if self.config.shared_writers:
                    # Replicated control plane: every Conflict leaves the
                    # verb CLASSIFIED (lost_race / stale_cache /
                    # ambiguous_timeout) and the cached view dropped — a
                    # conflicting peer claim proves the view wrong, and
                    # the retry must re-plan from fresh truth.
                    cause = self._classify_conflict(pod_name, namespace,
                                                    now)
                    with self._cache_lock:
                        self._cached_state = None
                    if tr.enabled:
                        tr.explain({
                            "verb": "bind",
                            "pod": f"{namespace or 'default'}/{pod_name}",
                            "node": node_name,
                            "conflict": {"cause": cause,
                                         "leg": "cas_patch"},
                        })
                raise BindError(f"bind race on {pod_name}: {e}",
                                reason="conflict", cause=cause) from e
            except NotFound as e:
                self.metrics.inc("bind_errors")
                raise BindError(f"bind race on {pod_name}: {e}",
                                reason="not_found") from e
            except ApiUnavailable as e:
                self.metrics.inc("bind_errors")
                self.metrics.inc("bind_unavailable")
                raise BindError(
                    f"api unavailable binding {pod_name}: {e}",
                    reason=("timeout" if isinstance(e, ApiTimeout)
                            else "unavailable")) from e
        if self.config.shared_writers:
            # Claim arbitration (replicated control plane): the per-pod
            # CAS above cannot see CROSS-POD chip overlap — a peer
            # binding a different pod onto the same chips from an equally
            # stale view sails through its own CAS.  Validate this bind's
            # claim against authoritative occupancy and retreat (wipe our
            # annotations, classified BindError) when ANY other live
            # claim overlaps — an earlier committer's check has already
            # passed, so only we can still back out; a concurrently
            # in-flight pair mutually retreats (safe, retried).  See
            # _claim_check for why no tie-break is sound.
            with tr.phase("claim"):
                self._claim_check(pod_name, namespace, node_name,
                                  placement, now, tr)
        # ``with``-managed span (release-on-all-paths rule): the former
        # manual __enter__/__exit__ pair leaked the span if anything in
        # the publish section raised — the with-form closes it on every
        # path, exception edges included, with identical deterministic
        # phase counts (wall-ms is telemetry either way).
        pub_span = tr.phase("publish")
        with pub_span:
            if self.informer is not None:
                # Write-through assume cache: the NEXT sort must see this bind
                # without waiting a watch round-trip, or it plans against
                # pre-bind state and hands out already-assigned chips (the
                # kube-scheduler cache pattern; the API server's CAS stays
                # authoritative either way).  Prefer the object bind_pod itself
                # returned (the fake API returns the bound pod — zero extra
                # RPCs); the real binding subresource returns a Status, so fall
                # back to a read-back there.
                new_token = None
                try:
                    if not (isinstance(bound_obj, dict)
                            and bound_obj.get("spec", {}).get("nodeName")
                            and bound_obj.get("metadata", {}).get("resourceVersion")):
                        bound_obj = self.api.get("pods", pod_name, namespace)
                    new_token = self.informer.observe("pods", bound_obj)
                # tpulint: disable=except-contract -- deliberate boundary: the bind is already committed; ANY read-back/mirror failure must become an unmirrored-bind gap (repaired later), never a bind error
                except Exception:
                    # The bind itself already succeeded, so a failed read-back
                    # (deleted pod, transient 5xx, network) must not surface as
                    # a bind error — but until the watch delivers this bind,
                    # the mirror may lack a committed placement, so later binds
                    # must not plan from it (double-booking would pass the
                    # per-pod CAS).  Record the gap; binds go authoritative
                    # until it is repaired (_repair_write_through).
                    self.metrics.inc("bind_observe_errors")
                    self._unmirrored_binds.add((namespace or "default", pod_name))
                # Delta fast path: when our own write is provably the ONLY
                # mirror content change since the state was built (observe
                # returns the post-install token atomically; expected = built
                # token + 1), publish a copy-on-write clone with this bind
                # applied instead of invalidating — the next verb reuses it,
                # and bind stays O(chips) instead of O(pods).
                published = False
                if (self.config.state_delta and new_token is not None
                        and state_token is not None
                        and state is self._cached_state):
                    try:
                        expected = (str(int(state_token[0]) + 1),)
                    except (ValueError, IndexError):
                        expected = None
                    if new_token == expected:
                        new_state = self._bind_delta_state(
                            state, pod_name, namespace, node_name, placement,
                            now, gang_id)
                        if new_state is not None:
                            new_state = self._carry_state_memos(state, new_state)
                            with self._cache_lock:
                                self._cached_state = new_state
                                self._cached_informer_version = new_token
                            # _cached_at deliberately NOT refreshed: it stamps
                            # when occupancy was last judged against the clock
                            # (assume-TTL expiry happens only at sync), and the
                            # 5 s age bound must keep holding under sustained
                            # bind traffic — a delta carries the original
                            # timestamp forward.
                            published = True
                            self.metrics.inc("bind_state_delta")
                if not published and not (self.config.state_delta
                                          and state_token is not None
                                          and state is self._cached_state):
                    # The delta could not apply and the cached state is not an
                    # informer-coherent (state, token) pair the event journal
                    # can fold forward — drop it; the next verb rebuilds from
                    # the (write-through-fresh) mirror.  When the pair IS
                    # coherent at its token (external events merely interleaved
                    # with our bind), it stays: the next verb folds the journal
                    # tail — including this bind's own write-through — in
                    # O(events) instead of re-syncing O(pods).
                    with self._cache_lock:
                        self._cached_state = None
            elif self.config.bind_from_cache:
                # Informer-less assume cache: apply our own bind to the
                # cached derived state so the next verb in the burst
                # reuses it instead of re-syncing.  In single-owner mode
                # (the sole-writer sim engine) the delta folds IN PLACE
                # (ClusterState.bind_inplace: an O(chips) note_bind
                # instead of the _cow clone; its FOLD_INPLACE kill switch
                # restores the copy-on-write clone byte-for-byte) and
                # memo eviction touches only the bound domain.  Under
                # shared_writers the sole-writer premise is void — racing
                # replica commits this cache never sees make an in-place
                # mutation a silent corruption — so the delta DOWNGRADES
                # to the copy-on-write with_bind clone (the same COW
                # discipline the informer path keeps for its lock-free
                # readers); staleness vs peers is then caught by the bind
                # verb's claim arbitration, never by trusting this cache.
                new_state = None
                pre_masks = None
                use_dirty = False
                if self.config.state_delta and state is self._cached_state:
                    pa = PodAssignment(
                        pod_name=pod_name, namespace=namespace or "default",
                        node_name=node_name, chips=list(placement.chips),
                        assigned=False, assume_time=now, gang_id=gang_id)
                    if self._single_owner:
                        use_dirty = (self.DIRTY_FOLD
                                     and ClusterState.FOLD_INPLACE)
                        if use_dirty:
                            # note_bind records the bound domain in
                            # _dirty_sids — no per-domain mask snapshot.
                            state._dirty_sids.clear()
                        else:
                            pre_masks = ({sid: dom.allocator.used_mask
                                          for sid, dom in
                                          state.domains.items()}
                                         if ClusterState.FOLD_INPLACE
                                         else None)
                        new_state = state.bind_inplace(pa)
                    else:
                        try:
                            new_state = state.with_bind(pa)
                        except ValueError:
                            new_state = None  # stale view — drop below
                if new_state is not None:
                    if new_state is state:
                        self._evict_state_memos(
                            state, pre_masks,
                            dirty=state._dirty_sids if use_dirty else None)
                    else:
                        new_state = self._carry_state_memos(state, new_state)
                    self.metrics.inc("bind_state_delta")
                with self._cache_lock:
                    self._cached_state = new_state

        decision = {
            "pod": f"{namespace}/{pod_name}",
            "node": node_name,
            "slice": dom.slice_id,
            "chips": [list(c) for c in placement.chips],
            "contiguous": placement.is_contiguous_box,
            "predicted_allreduce_gbps": placement.score_gbps,
            "gang": gang_id,
            "time": now,
        }
        self.decisions.append(decision)
        del self.decisions[:-max(1, self.config.decisions_retention)]
        if tr.enabled:
            tr.explain(self._bind_explain(
                state, decision, k, gang, gang_ctx, memo_base))
        self.metrics.inc("bind_success")
        self.metrics.observe_ms("bind", (self._wall() - t0) * 1e3)
        return decision

    def _bind_explain(self, state: ClusterState, decision: dict, k: int,
                      gang, gang_ctx: dict | None,
                      memo_base: tuple[int, ...]) -> dict:
        """The bind verb's explain record (traced path only): the decision
        itself, the gang-search stats, and a per-node breakdown — planned/
        chosen nodes with their placement score, every other TPU node with
        a structured rejection reason (wrong generation, insufficient free
        chips, gang domain mismatch, outside the chosen host box)."""
        node_name = decision["node"]
        chosen_dom = state.domain_of_node(node_name)
        plan = gang_ctx["plan"] if gang_ctx is not None else {}
        plan_doms = self._plan_domains(state, plan) or (
            {chosen_dom.slice_id} if chosen_dom else set())
        nodes = []
        rejects_kept = rejects_omitted = 0
        for nname in sorted(state._dom_by_node):
            p = plan.get(nname)
            if nname == node_name:
                nodes.append({"node": nname, "chosen": True,
                              "score_gbps": round(
                                  decision["predicted_allreduce_gbps"], 3)})
            elif p is not None:
                nodes.append({"node": nname, "planned": True,
                              "score_gbps": round(p.score_gbps, 3)})
            elif rejects_kept >= self._EXPLAIN_REJECT_CAP:
                # Chosen/planned nodes are always listed; detailed
                # rejections are capped so a bind explain on a
                # thousands-node fleet stays KB-sized (see the cap const).
                rejects_omitted += 1
            else:
                rejects_kept += 1
                if gang_ctx is not None:
                    reason = self._gang_reject_reason(
                        state, k, nname, gang_ctx, plan_doms)
                else:
                    free = state.free_mask_on_node(nname).bit_count()
                    reason = ("insufficient_free_chips" if free < k
                              else "not_selected")
                nodes.append({"node": nname, "rejected": reason})
        record = {
            "verb": "bind",
            "pod": decision["pod"],
            "node": node_name,
            "t": round(decision["time"], 6),
            "k": k,
            "chips": decision["chips"],
            "contiguous": decision["contiguous"],
            "score_gbps": round(decision["predicted_allreduce_gbps"], 3),
            "gang": (self._gang_explain(gang, gang_ctx)
                     if gang is not None else None),
            "nodes": nodes,
            "memo": self._memo_delta(memo_base),
        }
        if rejects_omitted:
            record["nodes_omitted"] = rejects_omitted
        return record
