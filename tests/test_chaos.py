"""tputopo.chaos: deterministic fault injection, the retry/backoff/
recovery hardening it exercises (scheduler bind legs, crash recovery,
GC/defrag transient tolerance, informer relist under dropped watches),
the gang-member meta index, and the invariant auditor."""

import json
import urllib.error
import urllib.request

import pytest

from tests.cluster import build_cluster
from tputopo.chaos import ChaosApi, FaultPlan, audit_engine
from tputopo.defrag import DefragController
from tputopo.extender import (ExtenderConfig, ExtenderHTTPServer,
                              ExtenderScheduler)
from tputopo.extender.gc import AssumptionGC
from tputopo.k8s import FakeApiServer, make_pod
from tputopo.k8s import objects as ko
from tputopo.k8s.fakeapi import Conflict
from tputopo.k8s.informer import Informer
from tputopo.k8s.retry import ApiUnavailable, RetryPolicy
from tputopo.sim.engine import SimEngine, run_trace
from tputopo.sim.report import SCHEMA_CHAOS, SCHEMA_WATERMARK
from tputopo.sim.trace import TraceConfig, generate_trace

from tests.test_informer import wait_until

GANG_KEY = "tpu.dev/gang-id"


def quiet_plan(**overrides):
    """An api-flake plan with every fault off unless overridden."""
    knobs = dict(conflict_prob=0.0, unavailable_prob=0.0, timeout_prob=0.0,
                 ambiguous_timeout_prob=0.0, crash_prob=0.0, node_flaps=0,
                 watch_drop_prob=0.0, watch_reorder_prob=0.0)
    knobs.update(overrides)
    return FaultPlan(0, "api-flake", **knobs)


# ---- FaultPlan / RetryPolicy ------------------------------------------------


def test_fault_plan_is_deterministic_per_seed():
    a, b = quiet_plan(unavailable_prob=0.3), quiet_plan(unavailable_prob=0.3)
    seq_a = [a.decide("x", 0.3, ("k",)) for _ in range(200)]
    seq_b = [b.decide("x", 0.3, ("k",)) for _ in range(200)]
    assert seq_a == seq_b
    assert a.injected == b.injected
    c = FaultPlan(1, "api-flake", unavailable_prob=0.3)
    assert seq_a != [c.decide("x", 0.3, ("k",)) for _ in range(200)]


def test_fault_plan_consecutive_cap_guarantees_progress():
    plan = quiet_plan()
    # Certain-hit fault: the cap must suppress the (max_consecutive+1)th
    # consecutive injection on one op key — the liveness contract.
    hits = [plan.decide("boom", 1.0, ("op",)) for _ in range(3)]
    assert hits == [True, True, False]
    assert plan.suppressed == 1
    # After a pass-through the streak restarts.
    assert plan.decide("boom", 1.0, ("op",)) is True


def test_op_fault_cap_spans_mixed_fault_kinds():
    """The liveness cap is per OPERATION, not per fault kind: alternating
    timeout/500 draws on one op must still cap at max_consecutive, so a
    caller retrying max_consecutive+1 times always gets through."""
    plan = quiet_plan()
    kinds = [("api_timeout", 0.5), ("api_unavailable", 0.5)]  # always hit
    outcomes = [plan.op_fault(("op",), kinds) for _ in range(6)]
    # Whatever mix of kinds fired, never more than 2 in a row land.
    assert outcomes[2] is None and outcomes[5] is None
    assert all(o is not None for o in outcomes[:2] + outcomes[3:5])
    assert plan.suppressed == 2


def test_high_rate_faults_never_crash_either_policy():
    """Review regression: retries exhausting mid-commit must abort the
    attempt cleanly (fault-classed None + reset), not crash the run or
    strand feasible jobs at the terminal drain."""
    chaos = {"profile": "api-flake",
             "timeout_prob": 0.35, "unavailable_prob": 0.35}
    for policy in ("naive", "ici"):
        eng = SimEngine(generate_trace(_small_cfg()), policy, chaos=chaos)
        eng.run_events()  # must not raise
        rs = eng.run_state()
        assert rs.chaos["invariants"]["ok"], \
            (policy, rs.chaos["invariants"]["violations"])
        j = eng.metrics.counts
        # The fault-free run places all 40 jobs; the drain's fault-retry
        # loop means chaos may not strand feasible work either.
        assert j["unplaced_at_end"] == 0, (policy, j)


def test_retry_policy_backs_off_then_succeeds_on_virtual_clock():
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

        def sleep(self, dt):
            self.t += dt

    clock = Clock()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ApiUnavailable("nope")
        return "ok"

    pol = RetryPolicy(max_attempts=4, base_backoff_s=0.5, jitter_frac=0.0)
    assert pol.call(flaky, clock=clock, sleep=clock.sleep) == "ok"
    assert len(calls) == 3
    assert clock.t == pytest.approx(0.5 + 1.0)  # exponential, no jitter

    # Attempts exhausted -> the transient error escapes.
    calls.clear()
    with pytest.raises(ApiUnavailable):
        pol.call(lambda: (_ for _ in ()).throw(ApiUnavailable("always")),
                 clock=clock, sleep=clock.sleep)

    # A deadline the next backoff would overshoot ends the loop early.
    calls.clear()
    with pytest.raises(ApiUnavailable):
        pol.call(flaky, clock=clock, sleep=clock.sleep, deadline_s=0.1)
    assert len(calls) == 1


# ---- ChaosApi injection -----------------------------------------------------


def test_chaos_api_injects_cas_conflict_before_apply():
    api = FakeApiServer()
    api.create("pods", make_pod("p1", chips=1))
    rv = api.get("pods", "p1", "default")["metadata"]["resourceVersion"]
    chaos = ChaosApi(api, quiet_plan(conflict_prob=1.0))
    with pytest.raises(Conflict):
        chaos.patch_annotations("pods", "p1", {"a": "b"}, "default",
                                expect_version=rv)
    # Injected BEFORE apply: the store is untouched.
    assert "a" not in api.get("pods", "p1",
                              "default")["metadata"]["annotations"]
    # Non-CAS patches never draw the conflict fault.
    chaos.patch_annotations("pods", "p1", {"a": "b"}, "default")
    assert chaos.plan.injected == {"cas_conflict": 1}


def test_bind_survives_ambiguous_timeout_via_reconciliation():
    """The nastiest injected fault: patch/bind APPLY, then time out.  The
    retried patch is idempotent; the retried bind conflicts against its
    own success and the scheduler must reconcile, not fail."""
    api, _ = build_cluster()
    chaos = ChaosApi(api, quiet_plan(ambiguous_timeout_prob=1.0))
    sched = ExtenderScheduler(chaos, ExtenderConfig())
    api.create("pods", make_pod("p1", chips=4))
    pod = api.get("pods", "p1", "default")
    scores = sched.sort(pod, ["node-0", "node-1", "node-2", "node-3"])
    best = max(scores, key=lambda s: (s["Score"], s["Host"]))
    assert best["Score"] > 0
    decision = sched.bind("p1", "default", best["Host"])
    assert decision["node"] == best["Host"]
    bound = api.get("pods", "p1", "default")
    assert bound["spec"]["nodeName"] == best["Host"]
    assert sched.metrics.counters["bind_ambiguous_recovered"] == 1
    assert sched.metrics.counters.get("retry_api_timeout", 0) >= 1
    assert sched.metrics.counters["bind_success"] == 1


def test_bind_transient_errors_retry_to_success():
    api, _ = build_cluster()
    chaos = ChaosApi(api, quiet_plan(unavailable_prob=1.0))  # capped at 2
    sched = ExtenderScheduler(chaos, ExtenderConfig())
    api.create("pods", make_pod("p1", chips=2))
    decision = sched.bind("p1", "default", "node-0")
    assert decision["node"] == "node-0"
    assert sched.metrics.counters["retry_api_unavailable"] >= 2
    assert "bind_errors" not in sched.metrics.counters


# ---- crash recovery ---------------------------------------------------------


def _gang_pods(api, gang, size, chips):
    labels = {GANG_KEY: gang, "tpu.dev/gang-size": str(size)}
    for m in range(size):
        api.create("pods", make_pod(f"{gang}-{m}", chips=chips,
                                    labels=labels))


def _bind_first_member(api, gang, chips):
    """Bind member 0 the way the extender would, then 'crash'."""
    sched = ExtenderScheduler(api, ExtenderConfig())
    pod = api.get("pods", f"{gang}-0", "default")
    scores = sched.sort(pod, ["node-0", "node-1", "node-2", "node-3"])
    best = max(scores, key=lambda s: (s["Score"], s["Host"]))
    assert best["Score"] > 0
    sched.bind(f"{gang}-0", "default", best["Host"])
    return best["Host"]


def test_recover_completes_feasible_in_flight_gang():
    api, _ = build_cluster()  # v5p:2x2x4 — 4 hosts x 4 chips
    _gang_pods(api, "g", 2, 4)
    _bind_first_member(api, "g", 4)
    # Fresh scheduler = the restarted extender (empty caches).
    sched2 = ExtenderScheduler(api, ExtenderConfig())
    outcome = sched2.recover()
    assert outcome["completed"] == ["default/g"]
    assert outcome["released"] == []
    for m in range(2):
        p = api.get("pods", f"g-{m}", "default")
        assert p["spec"].get("nodeName"), f"member {m} not bound"
        assert p["metadata"]["annotations"].get(ko.ANN_GROUP)
    assert sched2.metrics.counters["crash_gangs_completed"] == 1


def test_recover_releases_gang_with_missing_member_pod():
    """A short roster can never complete: binding everything that exists
    would still leave the gang partial, so recover() must release it —
    not declare a 3-of-4 gang 'completed' because every bind succeeded."""
    api, _ = build_cluster()
    _gang_pods(api, "g", 2, 4)
    _bind_first_member(api, "g", 4)
    # Member 1's pod vanished while the extender was down (evicted and
    # not yet recreated by the job controller).
    api.delete("pods", "g-1", "default")
    sched2 = ExtenderScheduler(api, ExtenderConfig())
    outcome = sched2.recover()
    assert outcome["completed"] == []
    assert outcome["released"] == ["default/g"]
    p0 = api.get("pods", "g-0", "default")
    assert ko.ANN_GROUP not in p0["metadata"]["annotations"]
    assert sched2.metrics.counters["crash_gangs_released"] == 1


def test_recover_releases_infeasible_in_flight_gang():
    api, _ = build_cluster()
    _gang_pods(api, "g", 2, 4)
    bound_node = _bind_first_member(api, "g", 4)
    # Capacity vanished while the extender was down: every OTHER node is
    # gone, so the remaining member can never place (one pod per host).
    for n in ["node-0", "node-1", "node-2", "node-3"]:
        if n != bound_node:
            api.delete("nodes", n)
    sched2 = ExtenderScheduler(api, ExtenderConfig())
    outcome = sched2.recover()
    assert outcome["completed"] == []
    assert outcome["released"] == ["default/g"]
    # Release-or-complete, never half: the bound member's assumptions are
    # wiped (the job controller requeues it); nothing is half-reserved.
    p0 = api.get("pods", "g-0", "default")
    assert ko.ANN_GROUP not in p0["metadata"]["annotations"]
    assert sched2.metrics.counters["crash_gangs_released"] == 1


# ---- chaos sim runs ---------------------------------------------------------


def _small_cfg(**kw):
    kw.setdefault("seed", 0)
    kw.setdefault("nodes", 16)
    kw.setdefault("arrivals", 40)
    return TraceConfig(**kw)


def _canon(report):
    r = dict(report)
    r.pop("throughput", None)
    r.pop("phase_wall", None)
    return json.dumps(r, sort_keys=True)


def test_chaos_run_deterministic_with_clean_invariants():
    cfg = _small_cfg()
    ra = run_trace(cfg, ["ici", "naive"], chaos="api-flake")
    rb = run_trace(cfg, ["ici", "naive"], chaos="api-flake")
    assert _canon(ra) == _canon(rb)
    assert ra["schema"] == SCHEMA_CHAOS
    assert ra["engine"]["chaos"]["profile"] == "api-flake"
    for name, rec in ra["policies"].items():
        c = rec["chaos"]
        assert c["invariants"]["ok"], (name, c["invariants"]["violations"])
        # Zero lost jobs: the arithmetic the auditor enforces.
        jobs = rec["jobs"]
        assert jobs["arrived"] == (jobs["completed"]
                                   + jobs["ghost_reclaimed"]
                                   + jobs["unplaced_at_end"])
        assert c["injected"], "profile injected nothing — dead chaos run"


def test_chaos_off_keeps_schema_and_omits_block():
    r = run_trace(_small_cfg(arrivals=10), ["ici"])
    assert r["schema"] == SCHEMA_WATERMARK
    assert "chaos" not in r["policies"]["ici"]
    assert "chaos" not in r["engine"]


def test_crash_storm_engine_ends_gangs_clean():
    """Acceptance: crash-restarts injected mid-gang-bind end with every
    gang fully bound or fully released+requeued — audited per event AND
    at the end; recovery work shows up in the reason-split counters."""
    eng = SimEngine(generate_trace(_small_cfg(arrivals=60)), "ici",
                    chaos="crash-storm", audit_every=7)
    eng.run_events()
    rs = eng.run_state()
    chaos = rs.chaos
    assert chaos["injected"].get("crash_restart", 0) >= 1
    assert chaos["invariants"]["ok"], chaos["invariants"]["violations"]
    assert not eng.audit_violations
    recovered = (chaos["retries"].get("crash_gangs_completed", 0)
                 + chaos["retries"].get("crash_gangs_released", 0))
    assert recovered >= 1
    assert chaos["retries"].get("crash_recoveries", 0) == \
        chaos["injected"]["crash_restart"]


def test_audit_engine_flags_planted_double_booking():
    eng = SimEngine(generate_trace(_small_cfg(arrivals=6)), "ici")
    eng.run_events()
    assert audit_engine(eng, final=True)["ok"]
    # Plant a corruption: a second pod claiming chips the ledger says
    # belong to someone else.
    sid = next(iter(eng.domains))
    chips = eng.chips_by_node["n00-00"][:2]
    api = eng.api
    api.create("pods", make_pod("evil-0", chips=2))
    api.patch_annotations("pods", "evil-0", {
        ko.ANN_GROUP: ko.coords_to_ann(chips),
        ko.ANN_ASSUME_TIME: str(eng.clock.t),
        ko.ANN_ASSIGNED: "true",
    }, "default")
    api.bind_pod("evil-0", "n00-00", "default")
    result = audit_engine(eng, final=False)
    assert not result["ok"]
    assert any("ledger_mismatch" in v or "double_booked" in v
               for v in result["violations"])


# ---- informer under watch faults (satellite) --------------------------------


def test_informer_relists_after_injected_watch_drop():
    api = FakeApiServer()
    api.create("nodes", ko.make_node("n1", chips=4))
    chaos = ChaosApi(api, quiet_plan(watch_drop_prob=1.0))
    inf = Informer(chaos, watch_timeout_s=0.5, relist_backoff_s=0.05).start()
    try:
        assert inf.wait_synced(10)
        for i in range(6):
            api.create("pods", make_pod(f"p{i}", chips=1))
        # Every watch stream Gone's after 1-3 events; the mirror still
        # converges because Gone -> relist is the recovery path.
        assert wait_until(lambda: len(inf.list("pods")) == 6)
        assert inf.metrics["relists"] >= 1
    finally:
        inf.stop()


def test_watch_reorder_tallies_only_when_it_lands():
    """`injected` records faults that LANDED (the module contract): a
    held event the stream tail delivers in its original position is NOT
    a reorder, and must not be counted as one."""
    api = FakeApiServer()
    _, rv = api.list_with_version("pods")
    api.create("pods", make_pod("only", chips=1))
    plan = quiet_plan(watch_reorder_prob=1.0)
    chaos = ChaosApi(api, plan)
    events = list(chaos.watch("pods", rv, timeout_s=0.1))
    # One event: held, then tail-delivered in order — nothing landed.
    assert [e["object"]["metadata"]["name"] for e in events
            if e["type"] != "BOOKMARK"] == ["only"]
    assert plan.injected.get("watch_reorder", 0) == 0

    # With a successor to overtake the held event, the reorder lands
    # (delivery order flips) and is tallied exactly once per landing.
    _, rv2 = api.list_with_version("pods")
    api.create("pods", make_pod("a", chips=1))
    api.create("pods", make_pod("b", chips=1))
    events = [e for e in chaos.watch("pods", rv2, timeout_s=0.1)
              if e["type"] != "BOOKMARK"]
    assert [e["object"]["metadata"]["name"] for e in events] == ["b", "a"]
    assert plan.injected.get("watch_reorder", 0) == 1


def test_informer_absorbs_reordered_watch_delivery():
    api = FakeApiServer()
    api.create("nodes", ko.make_node("n1", chips=4))
    chaos = ChaosApi(api, quiet_plan(watch_reorder_prob=1.0))
    inf = Informer(chaos, watch_timeout_s=0.5).start()
    try:
        assert inf.wait_synced(10)
        api.create("pods", make_pod("p1", chips=1))
        for i in range(10):
            api.patch_annotations("pods", "p1", {"i": str(i)}, "default")

        def settled():
            try:
                pod = inf.get("pods", "p1", "default")
            except Exception:
                return False
            return pod["metadata"]["annotations"].get("i") == "9"

        # Newest-wins upserts must land on the final value despite every
        # other event being delivered late.
        assert wait_until(settled)
    finally:
        inf.stop()


def test_journal_gap_during_in_flight_fold_falls_back_cleanly():
    """A derived state whose informer token fell off the bounded journal
    (a churn burst outran the window) must rebuild, not fold garbage —
    counted under the journal_gap reason."""
    api, _ = build_cluster()
    inf = Informer(api, watch_timeout_s=0.5).start()
    try:
        assert inf.wait_synced(10)
        sched = ExtenderScheduler(api, ExtenderConfig(), informer=inf)
        api.create("pods", make_pod("px", chips=1))
        assert wait_until(lambda: len(inf.list("pods")) == 1)
        pod = api.get("pods", "px", "default")
        sched.sort(pod, ["node-0"])  # builds the (state, token) pair
        assert sched._cached_informer_version is not None
        # Outrun the 256-entry journal while the fold is in flight.
        for i in range(300):
            api.patch_annotations("pods", "px", {"i": str(i)}, "default")
        assert wait_until(lambda: inf.get(
            "pods", "px", "default")["metadata"]["annotations"].get("i")
            == "299")
        sched.sort(api.get("pods", "px", "default"), ["node-0"])
        c = sched.metrics.counters
        assert c.get("state_delta_fallback_journal_gap", 0) >= 1
        assert c.get("state_full_rebuilds", 0) >= 2
    finally:
        inf.stop()


# ---- gang-member meta index (satellite) -------------------------------------


def _filtered(api, gang_id, namespace="default"):
    return api.list("pods", lambda p: (
        p["metadata"].get("namespace", "default") == namespace
        and ({**p["metadata"].get("annotations", {}),
              **p["metadata"].get("labels", {})}).get(GANG_KEY) == gang_id))


def test_meta_index_tracks_create_patch_delete_recreate():
    api = FakeApiServer()
    names = lambda objs: [o["metadata"]["name"] for o in objs]  # noqa: E731
    api.create("pods", make_pod("a-0", labels={GANG_KEY: "a"}))
    api.create("pods", make_pod("a-1", labels={GANG_KEY: "a"}))
    api.create("pods", make_pod("solo"))
    assert names(api.list_by_meta("pods", GANG_KEY, "a")) == \
        names(_filtered(api, "a")) == ["a-0", "a-1"]
    # Annotation-only membership (the bind-time stamp) joins the index.
    api.patch_annotations("pods", "solo", {GANG_KEY: "a"}, "default")
    assert names(api.list_by_meta("pods", GANG_KEY, "a")) == \
        ["a-0", "a-1", "solo"]
    # A label patch MOVES membership.
    api.patch_labels("pods", "a-1", {GANG_KEY: "b"}, "default")
    assert names(api.list_by_meta("pods", GANG_KEY, "a")) == ["a-0", "solo"]
    assert names(api.list_by_meta("pods", GANG_KEY, "b")) == ["a-1"]
    # Labels shadow annotations (merged-meta precedence).
    api.patch_labels("pods", "solo", {GANG_KEY: "c"}, "default")
    assert names(api.list_by_meta("pods", GANG_KEY, "a")) == ["a-0"]
    # Delete/recreate cycles stay exact.
    api.delete("pods", "a-0", "default")
    assert api.list_by_meta("pods", GANG_KEY, "a") == []
    api.create("pods", make_pod("a-0", labels={GANG_KEY: "a"}))
    assert names(api.list_by_meta("pods", GANG_KEY, "a")) == ["a-0"]
    # Unindexed keys refuse loudly rather than scanning or lying.
    with pytest.raises(KeyError):
        api.list_by_meta("pods", "some/other-label", "x")


def test_gang_members_uses_index_and_matches_filter():
    api, _ = build_cluster()
    _gang_pods(api, "g", 3, 4)
    api.create("pods", make_pod("noise", chips=1))
    sched = ExtenderScheduler(api, ExtenderConfig())
    got = sched._gang_members("default", "g")
    assert [p["metadata"]["name"] for p in got] == ["g-0", "g-1", "g-2"]
    assert [p["metadata"]["name"] for p in got] == \
        [p["metadata"]["name"] for p in _filtered(api, "g")]
    # Namespace scoping still holds through the index path.
    assert sched._gang_members("other", "g") == []


def test_informer_mirror_index_matches_api():
    api = FakeApiServer()
    api.create("nodes", ko.make_node("n1", chips=4))
    inf = Informer(api, watch_timeout_s=0.5).start()
    try:
        assert inf.wait_synced(10)
        _gang_pods(api, "g", 2, 4)
        assert wait_until(lambda: len(inf.list("pods")) == 2)
        assert [p["metadata"]["name"]
                for p in inf.list_by_meta("pods", GANG_KEY, "g")] == \
            ["g-0", "g-1"]
        api.delete("pods", "g-1", "default")
        assert wait_until(lambda: len(
            inf.list_by_meta("pods", GANG_KEY, "g")) == 1)
    finally:
        inf.stop()


# ---- GC / defrag transient tolerance ----------------------------------------


class _FlakyPatchApi:
    """Raises ApiUnavailable on the first N patch_annotations calls."""

    def __init__(self, api, failures):
        self._api = api
        self.failures = failures

    def __getattr__(self, name):
        return getattr(self._api, name)

    def patch_annotations(self, *a, **kw):
        if self.failures > 0:
            self.failures -= 1
            raise ApiUnavailable("injected")
        return self._api.patch_annotations(*a, **kw)


def test_gc_sweep_survives_transient_release_errors():
    api, _ = build_cluster()
    api.create("pods", make_pod("stale-0", chips=2))
    api.patch_annotations("pods", "stale-0", {
        ko.ANN_GROUP: "0,0,0;1,0,0",
        ko.ANN_ASSUME_TIME: "0.0",
        ko.ANN_ASSIGNED: "false",
    }, "default")
    api.bind_pod("stale-0", "node-0", "default")
    flaky = _FlakyPatchApi(api, failures=1)
    gc = AssumptionGC(flaky, assume_ttl_s=60.0, clock=lambda: 1000.0)
    # First sweep: the release fails transiently — skipped, NOT raised.
    assert gc.sweep() == []
    # Next sweep retries and releases it durably.
    assert gc.sweep() == ["default/stale-0"]
    anns = api.get("pods", "stale-0", "default")["metadata"]["annotations"]
    assert ko.ANN_GROUP not in anns


def test_defrag_verify_failure_replans_instead_of_wedging():
    api, _ = build_cluster()
    # Checkerboard: two quads pinning hosts 0 and 2 (test_defrag's shape).
    from tests.test_defrag import occupy, synced_state
    state = synced_state(api)
    dom = next(iter(state.domains.values()))
    nodes = [dom.node_by_host[h] for h in sorted(dom.node_by_host)]
    chips = {n: list(dom.chips_by_node[n]) for n in nodes}
    occupy(api, "quad-a-0", nodes[0], chips[nodes[0]])
    occupy(api, "quad-c-0", nodes[2], chips[nodes[2]])
    ctl = DefragController(api, clock=lambda: 1000.0, assume_ttl_s=60.0,
                           hysteresis=2, cooldown_s=0.0,
                           evict=lambda v: None)  # evictions never land
    demands = [(2, 4)]
    assert ctl.run_cycle(demands=demands)["reason"] == "hysteresis"
    rec = ctl.run_cycle(demands=demands)
    assert rec["action"] == "executed" and rec["restored"] is False
    assert ctl.counters["verify_failed"] == 1
    assert ctl.counters.get("verify_replans") == 1
    # Re-plan, not wedge: the failed verify carries the pressure streak,
    # so the very next cycle (cooldown permitting) plans and acts again
    # instead of re-earning the hysteresis from zero.
    rec3 = ctl.run_cycle(demands=demands)
    assert rec3["action"] == "executed"
    assert ctl.counters["plans_executed"] == 2


def test_defrag_eviction_tolerates_transient_delete_errors():
    api, _ = build_cluster()
    from tests.test_defrag import occupy, synced_state
    state = synced_state(api)
    dom = next(iter(state.domains.values()))
    nodes = [dom.node_by_host[h] for h in sorted(dom.node_by_host)]
    chips = {n: list(dom.chips_by_node[n]) for n in nodes}
    occupy(api, "quad-a-0", nodes[0], chips[nodes[0]])
    occupy(api, "quad-c-0", nodes[2], chips[nodes[2]])

    class _FlakyDelete:
        def __init__(self, api):
            self._api = api
            self.fail_next = 3  # < RetryPolicy.max_attempts

        def __getattr__(self, name):
            return getattr(self._api, name)

        def delete(self, *a, **kw):
            if self.fail_next > 0:
                self.fail_next -= 1
                raise ApiUnavailable("injected")
            return self._api.delete(*a, **kw)

    ctl = DefragController(_FlakyDelete(api), clock=lambda: 1000.0,
                           assume_ttl_s=60.0, hysteresis=1, cooldown_s=0.0)
    rec = ctl.run_cycle(demands=[(2, 4)])
    # The retried deletes eventually land; the migration verifies.
    assert rec["action"] == "executed"
    assert rec["restored"] is True


# ---- hardened HTTP surface (satellite) --------------------------------------


def test_debug_endpoints_fail_with_structured_500_and_counter():
    api, _ = build_cluster()
    config = ExtenderConfig()
    sched = ExtenderScheduler(api, config)
    srv = ExtenderHTTPServer(sched, config, port=0).start()
    try:
        host, port = srv.address

        def get(path):
            return urllib.request.urlopen(f"http://{host}:{port}{path}",
                                          timeout=5)

        boom = RuntimeError("kaboom")

        def exploding_state(*a, **kw):
            raise boom

        sched._state = exploding_state
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/state")
        assert ei.value.code == 500
        body = json.loads(ei.value.read())
        # Structured: type/message/path, no traceback text.
        assert body["error"]["type"] == "RuntimeError"
        assert body["error"]["message"] == "kaboom"
        assert body["error"]["path"] == "/state"
        assert "Traceback" not in json.dumps(body)
        assert sched.metrics.counters["http_internal_errors"] == 1
        # The failure is itself scrape-able; /metrics still serves.
        with get("/metrics") as resp:
            text = resp.read().decode()
        assert "tputopo_extender_http_internal_errors_total 1" in text
    finally:
        srv.stop()


def test_http_handler_carries_request_deadline():
    api, _ = build_cluster()
    config = ExtenderConfig(http_timeout_s=7.5)
    sched = ExtenderScheduler(api, config)
    srv = ExtenderHTTPServer(sched, config, port=0)
    try:
        assert srv.httpd.RequestHandlerClass.timeout == 7.5
    finally:
        srv.httpd.server_close()
