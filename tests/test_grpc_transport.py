"""Wire-level device-plugin tests (VERDICT r1 #1): Register / ListAndWatch /
Allocate as real gRPC frames over unix sockets, against the checked-in
v1beta1 proto encoding — no in-process shortcuts.  The kubelet side is
FakeKubeletGrpcServer, which (like the real kubelet) dials back to the
plugin's socket after Register."""

import pytest

pytest.importorskip("grpc")

from tests.cluster import probe_for
from tputopo.deviceplugin import api
from tputopo.deviceplugin.grpc_transport import (FakeKubeletGrpcServer,
                                                 GrpcKubelet)
from tputopo.deviceplugin.plugin import TpuDevicePlugin
from tputopo.k8s import FakeApiServer, make_pod
from tputopo.k8s import objects as ko


@pytest.fixture()
def wire(tmp_path):
    kubelet = FakeKubeletGrpcServer(str(tmp_path)).start()
    transport = GrpcKubelet(kubelet_dir=str(tmp_path))
    apiserver = FakeApiServer()
    plugin = TpuDevicePlugin(
        node_name="node-0", slice_id="slice-a", kubelet=transport,
        api_server=apiserver, probe=probe_for("v5p:2x2x1@0"),
        clock=lambda: 1000.0)
    plugin.start()
    yield kubelet, transport, apiserver, plugin
    transport.stop()
    kubelet.stop()


def test_register_and_listandwatch_over_the_wire(wire):
    kubelet, transport, apiserver, plugin = wire
    assert [r.resource_name for r in kubelet.registrations] == [ko.RESOURCE_CHIPS]
    assert kubelet.registrations[0].version == api.API_VERSION
    devices = kubelet.wait_for_devices()
    assert sorted(devices) == ["0,0,0", "0,1,0", "1,0,0", "1,1,0"]
    assert all(d.health == api.HEALTHY for d in devices.values())
    # Plugin also published its node annotations during start().
    anns = apiserver.get("nodes", "node-0")["metadata"]["annotations"]
    assert anns[ko.ANN_SLICE_ID] == "slice-a"
    # Kubelet fetched options during its dial-back.
    assert kubelet.options is not None
    assert kubelet.options.pre_start_required is False


def test_health_flip_streams_new_frame(wire):
    kubelet, transport, apiserver, plugin = wire
    kubelet.wait_for_devices()
    kubelet.clear_update_flag()
    plugin.set_health("0,0,0", healthy=False)
    devices = kubelet.wait_for_devices()
    assert devices["0,0,0"].health == api.UNHEALTHY
    assert devices["0,1,0"].health == api.HEALTHY


def test_allocate_over_the_wire_confirms_handshake(wire):
    kubelet, transport, apiserver, plugin = wire
    kubelet.wait_for_devices()
    # Stage the extender's half of the handshake: a bound pod with a fresh
    # unconfirmed assignment (design.md:227-232).
    apiserver.create("pods", make_pod(
        "w", chips=2, node_name="node-0",
        annotations={ko.ANN_GROUP: "0,0,0;0,1,0",
                     ko.ANN_ASSUME_TIME: "995", ko.ANN_ASSIGNED: "false"}))
    resp = kubelet.allocate(ko.RESOURCE_CHIPS, ["0,0,0", "0,1,0"])
    envs = resp.container_responses[0].envs
    assert envs["TPU_VISIBLE_CHIPS"] == "0,1"
    assert envs["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
    anns = apiserver.get("pods", "w", "default")["metadata"]["annotations"]
    assert anns[ko.ANN_ASSIGNED] == "true"


def test_allocate_error_surfaces_as_grpc_status(wire):
    import grpc

    kubelet, transport, apiserver, plugin = wire
    kubelet.wait_for_devices()
    # Reserved-chip clash: a live 2-chip assumption holds 0,0,0; a 1-device
    # kubelet-picked allocate (no matching pending pod) must be refused
    # (INVALID_ARGUMENT on the wire).
    apiserver.create("pods", make_pod(
        "holder", chips=2, node_name="node-0",
        annotations={ko.ANN_GROUP: "0,0,0;0,1,0",
                     ko.ANN_ASSUME_TIME: "999", ko.ANN_ASSIGNED: "false"}))
    with pytest.raises(grpc.RpcError) as ei:
        kubelet.allocate(ko.RESOURCE_CHIPS, ["0,0,0"])
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert "reserved" in ei.value.details()


def test_stale_plugin_socket_is_replaced(tmp_path):
    """A dead plugin's socket file must not wedge restart (real kubelet
    plugins unlink stale sockets at bring-up)."""
    sock = tmp_path / "tputopo.sock"
    sock.write_bytes(b"")  # stale file, not a listening socket
    kubelet = FakeKubeletGrpcServer(str(tmp_path)).start()
    transport = GrpcKubelet(kubelet_dir=str(tmp_path))
    plugin = TpuDevicePlugin(
        node_name="node-0", slice_id="slice-a", kubelet=transport,
        api_server=FakeApiServer(), probe=probe_for("v5p:2x2x1@0"),
        clock=lambda: 1000.0)
    plugin.start()
    try:
        assert kubelet.wait_for_devices()
    finally:
        transport.stop()
        kubelet.stop()


def test_serve_cli_binds_socket_and_registers(tmp_path):
    """`--serve` end-to-end as a subprocess: probes (fake), registers with a
    real Registration gRPC server over the kubelet dir, serves DevicePlugin
    on its own socket, exits after --max-iterations heartbeats."""
    import os
    import subprocess
    import sys

    kubelet = FakeKubeletGrpcServer(str(tmp_path)).start()
    try:
        env = dict(os.environ, TPUTOPO_FAKE="v5p:2x2x1@0")
        proc = subprocess.run(
            [sys.executable, "-m", "tputopo.deviceplugin", "--serve",
             "--kubelet-dir", str(tmp_path), "--interval", "0.1",
             "--max-iterations", "3", "--node-name", "node-z"],
            env=env, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        devices = kubelet.wait_for_devices()
        assert sorted(devices) == ["0,0,0", "0,1,0", "1,0,0", "1,1,0"]
        assert kubelet.registrations[0].resource_name == ko.RESOURCE_CHIPS
        assert '"event": "serving"' in proc.stdout
    finally:
        kubelet.stop()


def test_serve_cli_exits_on_kubelet_restart(tmp_path):
    """Kubelet restart wipes the device-plugin dir; the agent must exit (the
    DaemonSet restarts it into a fresh registration) rather than keep
    serving a socket the kubelet no longer knows."""
    import os
    import subprocess
    import sys

    kubelet = FakeKubeletGrpcServer(str(tmp_path)).start()
    try:
        env = dict(os.environ, TPUTOPO_FAKE="v5p:2x2x1@0")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tputopo.deviceplugin", "--serve",
             "--kubelet-dir", str(tmp_path), "--interval", "0.2",
             "--node-name", "node-r"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        kubelet.wait_for_devices()
        os.unlink(tmp_path / "tputopo-node-r.sock")  # kubelet dir wiped
        out, err = proc.communicate(timeout=30)
        assert proc.returncode == 4, (proc.returncode, err)
        assert "kubelet-restarted" in err
    finally:
        kubelet.stop()


def test_preferred_allocation_over_the_wire(wire):
    """VERDICT r2 #8: the plugin serves GetPreferredAllocation, so even an
    unmanaged pod's kubelet pick is ICI-adjacent."""
    kubelet, transport, apiserver, plugin = wire
    kubelet.wait_for_devices()
    assert kubelet.options.get_preferred_allocation_available is True
    # 2-of-3 where one pair is diagonal: must come back adjacent.
    picks = kubelet.get_preferred_allocation(
        ko.RESOURCE_CHIPS, ["0,0,0", "0,1,0", "1,1,0"], [], 2)
    assert picks == [["0,0,0", "0,1,0"]] or picks == [["0,1,0", "1,1,0"]]
    # must_include pins the diagonal corner; its adjacent mate is chosen.
    picks = kubelet.get_preferred_allocation(
        ko.RESOURCE_CHIPS, ["0,0,0", "0,1,0", "1,1,0"], ["1,1,0"], 2)
    assert picks == [["0,1,0", "1,1,0"]]


def test_preferred_allocation_error_is_invalid_argument(wire):
    import grpc

    kubelet, transport, apiserver, plugin = wire
    kubelet.wait_for_devices()
    with pytest.raises(grpc.RpcError) as ei:
        kubelet.get_preferred_allocation(
            ko.RESOURCE_CHIPS, ["0,0,0"], [], 2)  # size > available
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
