"""tputopo.sim: determinism (byte-identical reports), virtual time, the
policy A/B contract, node-churn eviction, the ghost/TTL-GC path, and the
shared ceil-rank quantile convention."""

import json
import subprocess
import sys
import time

import pytest

from tputopo.sim.engine import SimEngine, run_trace
from tputopo.sim.policies import available_policies
from tputopo.sim.report import SCHEMA_WATERMARK
from tputopo.sim.trace import TraceConfig, generate_trace

# Small two-domain fleet: v5p:2x2x4 = 16 chips over 4 hosts per domain.
SMALL = dict(nodes=8, spec="v5p:2x2x4", arrivals=40)


def test_trace_generation_is_deterministic_and_seed_sensitive():
    cfg = TraceConfig(seed=7, **SMALL)
    assert generate_trace(cfg) == generate_trace(cfg)
    assert generate_trace(cfg) != generate_trace(TraceConfig(seed=8, **SMALL))


def test_trace_geometry():
    cfg = TraceConfig(**SMALL)
    assert cfg.hosts_per_domain == 4
    assert cfg.n_domains == 2
    assert cfg.total_chips == 32
    assert cfg.chips_per_host == 4


def _canon(report: dict) -> str:
    """Report bytes under the determinism contract: everything except the
    wall-clock ``throughput`` and ``phase_wall`` blocks (the two
    documented exceptions)."""
    report = dict(report)
    report.pop("throughput", None)
    report.pop("phase_wall", None)
    return json.dumps(report, sort_keys=True)


def test_report_is_byte_identical_across_runs():
    """The determinism contract: same seed + config => byte-identical
    report JSON across two independent engine runs (the property that
    makes sim reports diffable across PRs).  The throughput block is the
    one documented wall-clock exception; its deterministic fields must
    still agree."""
    cfg = TraceConfig(seed=0, **SMALL)
    ra = run_trace(cfg, ["ici", "naive"])
    rb = run_trace(cfg, ["ici", "naive"])
    assert _canon(ra) == _canon(rb)
    assert ra["throughput"]["events"] == rb["throughput"]["events"]
    assert ra["throughput"]["events"] > 0
    c = run_trace(TraceConfig(seed=1, **SMALL), ["ici", "naive"])
    assert _canon(ra) != _canon(c)  # the seed actually steers the trace


def test_parallel_jobs_report_matches_sequential():
    """run_trace(jobs=N) replays the policies in worker processes; the
    report must stay byte-identical to the sequential run (modulo the
    wall-clock throughput block, whose deterministic fields still agree
    except for the worker count)."""
    cfg = TraceConfig(seed=0, **SMALL)
    seq = run_trace(cfg, ["ici", "naive"], jobs=1)
    par = run_trace(cfg, ["ici", "naive"], jobs=2)
    assert _canon(seq) == _canon(par)
    assert seq["throughput"]["events"] == par["throughput"]["events"]
    assert seq["throughput"]["jobs"] == 1
    assert par["throughput"]["jobs"] == 2


def test_runs_on_virtual_time():
    """Hours of simulated cluster time must cost (much) less wall clock
    than simulated — the no-time.sleep-proportionality contract."""
    cfg = TraceConfig(seed=0, **SMALL)
    t0 = time.perf_counter()
    report = run_trace(cfg, ["ici"])
    wall_s = time.perf_counter() - t0
    assert report["virtual_horizon_s"] > 600.0
    assert wall_s < min(60.0, report["virtual_horizon_s"] / 10)


def test_ab_policies_show_nonzero_delta():
    """ICI-aware vs count-only over one identical trace: the bandwidth
    score must separate the policies (the Gaia Exp.5/6 analog)."""
    cfg = TraceConfig(seed=0, **SMALL)
    report = run_trace(cfg, ["ici", "naive"])
    deltas = report["ab"]["deltas"]["ici-vs-naive"]
    assert deltas["ici_bw_score_mean_vs_ideal"] != 0.0
    # Topology awareness must WIN on placement quality at this config
    # (verified stable for this seed; the delta is ~+0.3).
    assert deltas["ici_bw_score_mean_vs_ideal"] > 0.05
    pols = report["policies"]
    assert (pols["ici"]["ici_bw_score"]["contiguous_frac"]
            >= pols["naive"]["ici_bw_score"]["contiguous_frac"])


def test_report_schema_has_required_metrics():
    cfg = TraceConfig(seed=0, nodes=4, spec="v5p:2x2x4", arrivals=15)
    report = run_trace(cfg, ["ici", "naive"])
    assert report["schema"] == SCHEMA_WATERMARK
    for p in report["policies"].values():
        assert {"p50", "p95", "mean", "max"} <= set(p["queue_wait_s"])
        assert "time_weighted_mean" in p["chip_utilization"]
        assert "time_weighted_mean" in p["fragmentation"]
        assert "mean_vs_ideal" in p["ici_bw_score"]
        assert p["jobs"]["arrived"] == 15
    assert 0.0 <= report["policies"]["ici"]["chip_utilization"]["peak"] <= 1.0


def test_node_failure_evicts_and_requeues():
    cfg = TraceConfig(seed=2, nodes=16, spec="v5p:2x2x4", arrivals=60,
                      node_failures=5, repair_mean_s=120.0)
    p = run_trace(cfg, ["ici"])["policies"]["ici"]
    assert p["preemptions"]["node_failures"] == 5
    assert p["preemptions"]["pods_evicted"] > 0
    assert p["preemptions"]["jobs_requeued"] > 0
    assert p["jobs"]["evicted_requeues"] == p["preemptions"]["jobs_requeued"]


def test_ghosts_are_reclaimed_by_ttl_gc_on_sim_time():
    """Every bound-but-never-confirmed job is reclaimed by the TTL GC
    running on the virtual clock — including ghosts placed by the final
    GC wake itself (no stranded assumptions at drain)."""
    cfg = TraceConfig(seed=1, nodes=4, spec="v5p:2x2x4", arrivals=10,
                      ghost_prob=1.0, node_failures=0)
    p = run_trace(cfg, ["ici"])["policies"]["ici"]
    assert p["jobs"]["completed"] == 0
    assert p["jobs"]["scheduled"] > 0
    assert p["jobs"]["ghost_reclaimed"] == p["jobs"]["scheduled"]
    assert p["gc"]["assumptions_released"] >= p["jobs"]["scheduled"]


def test_engine_ledger_cross_checks_every_policy():
    """The engine's independent chip ledger sees every chip exactly once
    per placement — run both policy families and a failure trace through
    it (a double-booking would raise SimError)."""
    cfg = TraceConfig(seed=3, nodes=8, spec="v5p:2x2x4", arrivals=30,
                      ghost_prob=0.2, node_failures=3, repair_mean_s=60.0)
    trace = generate_trace(cfg)
    for name in ("ici", "naive"):
        engine = SimEngine(trace, name)
        engine.run()
        assert engine.placed_chips == len(engine.ledger)


def test_infeasible_queue_heads_do_not_starve_feasible_jobs():
    """>= budget permanently-infeasible gangs (8 replicas in a 4-host
    domain, no multislice label) parked at the queue head must not eat
    the per-wake backfill budget forever: the rotating scan window plus
    the terminal drain guarantee every feasible job is eventually placed,
    so unplaced_at_end equals exactly the never-feasible job count."""
    cfg = TraceConfig(seed=0, nodes=8, spec="v5p:2x2x4", arrivals=120,
                      node_failures=0)
    infeasible = sum(1 for j in generate_trace(cfg).jobs
                     if j.replicas > 4 and not j.multislice)
    assert infeasible > 0  # the trace actually contains stuck heads
    p = run_trace(cfg, ["ici"])["policies"]["ici"]
    assert p["jobs"]["unplaced_at_end"] == infeasible


def test_policy_registry_wires_baselines():
    names = available_policies()
    assert "ici" in names
    assert "naive" in names
    assert "spread" in names  # registered via topology.baselines
    from tputopo.topology.baselines import BASELINE_PICKERS, get_picker
    assert get_picker("naive") is not None
    with pytest.raises(KeyError, match="unknown baseline picker"):
        get_picker("nope")
    # Late registration is visible without re-imports (dynamic lookup).
    BASELINE_PICKERS["late"] = BASELINE_PICKERS["naive"]
    try:
        assert "late" in available_policies()
    finally:
        del BASELINE_PICKERS["late"]


def test_cli_emits_deterministic_json(tmp_path):
    """python -m tputopo.sim prints one parseable JSON report to stdout
    (wall telemetry on stderr only) and --out writes the same bytes."""
    out = tmp_path / "report.json"
    cmd = [sys.executable, "-m", "tputopo.sim", "--nodes", "4",
           "--spec", "v5p:2x2x4", "--arrivals", "12", "--seed", "0",
           "--policies", "ici,naive", "--out", str(out)]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout)
    assert report["schema"] == SCHEMA_WATERMARK
    assert list(report["policies"]) == ["ici", "naive"]
    assert json.loads(out.read_text()) == report
    assert "wall" in proc.stderr  # telemetry stays off stdout


def test_cli_rejects_unknown_policy():
    proc = subprocess.run(
        [sys.executable, "-m", "tputopo.sim", "--policies", "bogus"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "unknown policies" in proc.stderr


def test_quantile_convention_is_ceil_rank_everywhere():
    """The satellite contract: Metrics.quantiles_ms, bench.pct, and the
    sim report all use xs[min(n-1, ceil(n*q)-1)] — p95 of 10 samples is
    the max, not the 9th value, and they agree on identical data."""
    import bench
    from tputopo.extender.scheduler import Metrics, quantile

    xs = [float(i) for i in range(1, 11)]  # 1..10
    assert quantile(xs, 0.95) == 10.0
    assert quantile(xs, 0.5) == 5.0
    m = Metrics()
    for x in xs:
        m.observe_ms("sort", x)
    assert m.p95_ms("sort") == 10.0 == bench.pct(xs, 0.95)
    assert m.p50_ms("sort") == 5.0 == bench.pct(xs, 0.5)
    assert quantile([3.0], 0.95) == 3.0


def test_sim_runs_clean_under_nocopy_guard():
    """Mutation-guard satellite, end to end: a whole engine run with the
    fake API's digest guard armed proves the production read path (policy
    place, scheduler sort/bind, GC sync) never mutates a nocopy result."""
    cfg = TraceConfig(seed=0, nodes=4, spec="v5p:2x2x4", arrivals=12,
                      ghost_prob=0.2)
    engine = SimEngine(generate_trace(cfg), "ici")
    engine.api.nocopy_guard = True
    engine.run()
    engine.api.verify_nocopy_digests()


# ---- fleet scale (the 1024-node / 10k-arrival standing trace's knobs) --------


def test_offered_load_derives_rate_and_stays_out_of_standard_describe():
    """The fleet-scale knob: offered_load derives rate_per_s from the
    fleet so one load figure scales from 64 to 1024 nodes; unset, it is
    absent from describe() (pre-fleet report bytes pinned)."""
    base = TraceConfig(seed=0, nodes=64, arrivals=10)
    assert "offered_load" not in base.describe()
    loaded = TraceConfig(seed=0, nodes=64, arrivals=10, offered_load=0.73)
    # rate = load * chips / (mean_job_chips * mean_duration); the 64-node
    # default fleet was hand-tuned to ~0.73 at rate 0.1 — the derived
    # rate must land in that neighborhood, not a different regime.
    assert loaded.rate_per_s == pytest.approx(0.1, rel=0.05)
    d = loaded.describe()
    assert d["offered_load"] == 0.73
    assert d["rate_per_s"] == loaded.rate_per_s
    # Scale invariance: 16x the fleet at the same load = 16x the rate.
    big = TraceConfig(seed=0, nodes=1024, arrivals=10, offered_load=0.73)
    assert big.rate_per_s == pytest.approx(16 * loaded.rate_per_s)
    with pytest.raises(ValueError):
        TraceConfig(workload="mixed", offered_load=0.5)
    with pytest.raises(ValueError):
        TraceConfig(offered_load=-1.0)


def test_fleet_flavored_trace_is_byte_deterministic():
    """A multi-domain offered-load trace (the fleet standing figure's
    shape, scaled to the fast tier) replays byte-identically, and the
    baselines ride the delta path: full drops bounded by node churn."""
    cfg = TraceConfig(seed=0, nodes=128, arrivals=250, offered_load=0.73)
    assert cfg.n_domains == 8
    ra = run_trace(cfg, ["ici", "naive"], flight_trace=False)
    rb = run_trace(cfg, ["ici", "naive"], flight_trace=False)
    assert _canon(ra) == _canon(rb)
    c = ra["policies"]["naive"]["scheduler"]
    assert c["invalidate_delta_applied"] > 0
    assert c["invalidate_full_drops"] <= 2 * cfg.node_failures
    assert c["invalidate_drops_avoided"] > c["invalidate_full_drops"]


@pytest.mark.slow
def test_fleet_trace_parallel_matches_sequential():
    """The CI fleet smoke's property at a slow-tier scale: the 256-node
    fleet trace under --jobs 2 emits the sequential run's bytes."""
    cfg = TraceConfig(seed=0, nodes=256, arrivals=600, offered_load=0.73)
    seq = run_trace(cfg, ["ici", "naive"], jobs=1, flight_trace=False)
    par = run_trace(cfg, ["ici", "naive"], jobs=2, flight_trace=False)
    assert _canon(seq) == _canon(par)
    assert seq["schema"] == SCHEMA_WATERMARK


@pytest.mark.slow
def test_sim_throughput_floor():
    """Perf smoke (slow tier): the replay's events/sec must not regress
    below a GENEROUS floor — post-optimization this config sustains
    ~500 events/s; the floor only catches an order-of-magnitude
    regression (e.g. the deepcopy chain or the windowed frag scan
    creeping back into the hot path), never host noise."""
    cfg = TraceConfig(seed=0, nodes=16, spec="v5p:2x2x4", arrivals=120)
    tp = run_trace(cfg, ["ici"])["throughput"]
    assert tp["events"] > 300  # the trace actually exercises the engine
    assert tp["events_per_s"] > 50.0, tp
