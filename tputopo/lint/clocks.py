"""Determinism and injected-clock checkers.

Two rules police the project's virtual-time discipline:

- ``determinism`` — deterministic modules (the sim, chaos, the defrag
  planner, topology math, the flight recorder) must not *call* wall-clock
  or ambient-entropy builtins.  Time flows through an injected ``clock``
  and randomness through a seeded rng; the ``clock=time.time``
  default-argument idiom is the allowed escape hatch and is recognized
  structurally (a default is a *reference*, never a call).  Seeded rng
  construction (``random.Random(0x7E7)``, ``np.random.SeedSequence`` /
  ``Philox`` / ``Generator`` / ``default_rng(seed)``) is allowed — the
  ban is on drawing entropy from the environment, not on owning an rng.
- ``clock`` — any function that *takes* a ``clock`` parameter has
  promised its caller virtual-time capability; calling a wall-clock
  builtin in its body breaks that promise silently (the sim would run
  fine and stop being deterministic).  Enforced package-wide.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tputopo.lint.core import Checker, Finding, Module, dotted_name

#: Module paths whose event streams / reports are part of the
#: byte-determinism contract (ROADMAP "standing evaluation discipline").
#: The defrag *controller* is deliberately absent: it is the production
#: loop and uses per-instance entropy for retry jitter by design.
DETERMINISTIC_PREFIXES = (
    "tputopo/sim/",
    "tputopo/chaos/",
    "tputopo/topology/",
    "tputopo/obs/",
)
DETERMINISTIC_FILES = ("tputopo/defrag/planner.py",)

#: Wall-clock / ambient-entropy callables, by static dotted name.
WALL_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.sleep",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})
ENTROPY_CALLS = frozenset({
    "os.urandom", "uuid.uuid1", "uuid.uuid4", "secrets.token_bytes",
    "secrets.token_hex", "secrets.randbits",
})

#: numpy.random constructors that are deterministic *given a seed* —
#: allowed even in deterministic modules (the trace generator is built
#: on Philox streams).
_NP_SEEDED_CTORS = frozenset({"SeedSequence", "Philox", "PCG64",
                              "Generator", "BitGenerator"})


def _is_seeded_rng_ctor(call: ast.Call, dotted: str) -> bool:
    """``random.Random(<seed>)`` / ``np.random.default_rng(<seed>)`` /
    any ``*.random.{SeedSequence,Philox,...}(...)`` — seeded, allowed."""
    leaf = dotted.rsplit(".", 1)[-1]
    if leaf in _NP_SEEDED_CTORS and ".random." in f".{dotted}":
        return True
    if dotted in ("random.Random", "np.random.default_rng",
                  "numpy.random.default_rng"):
        return bool(call.args or call.keywords)  # seedless -> OS entropy
    return False


def _banned_call(call: ast.Call) -> str | None:
    dotted = dotted_name(call.func)
    if dotted is None:
        return None
    if dotted in WALL_CLOCK_CALLS:
        return f"wall-clock call {dotted}()"
    if dotted in ENTROPY_CALLS:
        return f"ambient-entropy call {dotted}()"
    first = dotted.split(".", 1)[0]
    if first in ("random",) or dotted.startswith(("np.random.",
                                                  "numpy.random.")):
        if not _is_seeded_rng_ctor(call, dotted):
            return (f"unseeded/ambient rng call {dotted}() — construct a "
                    "seeded generator and inject it")
    return None


class DeterminismChecker(Checker):
    """No wall clock or ambient entropy in deterministic modules."""

    rule = "determinism"
    description = ("deterministic modules (sim/, chaos/, topology/, obs/, "
                   "defrag/planner.py) must route time through an injected "
                   "clock and randomness through a seeded rng")

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith(DETERMINISTIC_PREFIXES)
                or relpath in DETERMINISTIC_FILES)

    def check_module(self, mod: Module) -> Iterable[Finding]:
        for node in mod.nodes():
            if isinstance(node, ast.Call):
                why = _banned_call(node)
                if why is not None:
                    yield Finding(
                        mod.relpath, node.lineno, node.col_offset, self.rule,
                        f"{why} in a deterministic module; inject a clock= "
                        "or seeded rng instead (the clock=time.time default "
                        "argument is the allowed escape hatch)")


class ClockDisciplineChecker(Checker):
    """A function taking ``clock`` must not also read the wall clock."""

    rule = "clock"
    description = ("functions with a clock parameter must not call "
                   "wall-clock builtins in their body")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("tputopo/")

    def check_module(self, mod: Module) -> Iterable[Finding]:
        # A finding needs BOTH a clock-taking def and a wall-clock call
        # spelled ``time.``/``datetime`` — most modules have neither.
        if "clock" not in mod.source or (
                "time." not in mod.source
                and "datetime" not in mod.source):
            return
        for node in mod.nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and self._takes_clock(node):
                yield from self._check_body(mod, node)

    @staticmethod
    def _takes_clock(fn: ast.FunctionDef) -> bool:
        a = fn.args
        names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
        return "clock" in names

    def _check_body(self, mod: Module, fn: ast.FunctionDef
                    ) -> Iterable[Finding]:
        stack: list[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._takes_clock(node):
                    continue  # nested fn re-promises; checked on its own
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if dotted in WALL_CLOCK_CALLS:
                    yield Finding(
                        mod.relpath, node.lineno, node.col_offset, self.rule,
                        f"{dotted}() called inside {fn.name}(), which takes "
                        "an injected clock — use the clock (or clock.sleep) "
                        "so virtual-time callers stay deterministic")
            stack.extend(ast.iter_child_nodes(node))
