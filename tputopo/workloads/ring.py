"""Ring attention — context parallelism over the ``sp`` mesh axis.

Long-context support for the flagship workload: with the sequence sharded
across devices, naive attention all-gathers K/V (peak memory O(S) per
device).  Ring attention instead rotates K/V chunks around the ``sp``
ring with `ppermute` — exactly one chunk resident per device per step —
merging partial results with the same online-softmax recurrence the flash
kernel uses.  Peak memory drops to O(S / n_sp) while the math stays
bit-equivalent to full attention.

This is why the scheduler's placement invariant matters: `ppermute` over
a contiguous slice's mesh axis rides physical ICI neighbor links
(jax.sharding lays logical axes onto torus axes — sharding.py), so each
rotation step is a single-hop transfer.  A scattered placement would turn
every step into multi-hop or DCN traffic.

GQA: K/V may arrive with fewer heads than Q (``kv_group`` > 1) — the
narrow tensors are what rotates (group-x less ICI traffic per step);
heads are expanded transiently at compute time.  Causality is handled by
global-position masking from each chunk's ring offset.  The rotation
runs ``lax.scan`` with the last rotation elided (n-1 transfers for n
chunks), and is reverse-differentiable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax import shard_map

NEG_INF = -1e30


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str, axis_size: int,
                         causal: bool = True, kv_group: int = 1) -> jax.Array:
    """Per-device body (call under shard_map): q [B, Sc, N, H], k/v
    [B, Sc, N/kv_group, H] local chunks; returns local [B, Sc, N, H]
    attention output as if computed over the full global sequence."""
    B, Sc, N, H = q.shape
    scale = 1.0 / (H ** 0.5)
    my = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32) * scale
    q_pos = my * Sc + jax.lax.broadcasted_iota(jnp.int32, (Sc, Sc), 0)

    def accumulate(carry, j, kc, vc):
        m, l, acc = carry
        kcf = kc.astype(jnp.float32)
        vcf = vc.astype(jnp.float32)
        if kv_group > 1:
            kcf = jnp.repeat(kcf, kv_group, axis=2)
            vcf = jnp.repeat(vcf, kv_group, axis=2)
        src = (my - j) % axis_size  # ring position this chunk came from
        s = jnp.einsum("bqnh,bknh->bnqk", qf, kcf)
        if causal:
            k_pos = src * Sc + jax.lax.broadcasted_iota(jnp.int32, (Sc, Sc), 1)
            s = jnp.where((k_pos <= q_pos)[None, None, :, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # alpha is [B, N, Sc, 1]; acc is [B, Sc, N, H] — align axes.
        acc = (acc * jnp.moveaxis(alpha, 1, 2) +
               jnp.einsum("bnqk,bknh->bqnh", p, vcf))
        return m_new, l, acc

    def step(carry, j):
        kc, vc, m, l, acc = carry
        m, l, acc = accumulate((m, l, acc), j, kc, vc)
        # Rotate the NARROW K/V to the next device; the final chunk's
        # rotation is elided (handled after the scan) — n-1 transfers.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, m, l, acc), None

    m0 = jnp.full((B, N, Sc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, N, Sc, 1), jnp.float32)
    acc0 = jnp.zeros((B, Sc, N, H), jnp.float32)
    if axis_size > 1:
        (kc, vc, m, l, acc), _ = jax.lax.scan(
            step, (k, v, m0, l0, acc0), jnp.arange(axis_size - 1))
    else:
        kc, vc, m, l, acc = k, v, m0, l0, acc0
    _, l, acc = accumulate((m, l, acc), axis_size - 1, kc, vc)
    denom = jnp.moveaxis(l, 1, 2)  # [B, Sc, N, 1]
    # A fully masked row (can't happen when causal includes self) would
    # divide by zero; guard anyway for non-causal degenerate shapes.
    out = acc / jnp.maximum(denom, 1e-30)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, plan, *,
                   causal: bool = True, kv_group: int = 1) -> jax.Array:
    """Global-array entry: q [B, S, N, H] (k/v may carry N/kv_group heads),
    logically global, laid out batch-over-dp, seq-over-sp, heads-over-tp
    on ``plan``'s mesh."""
    n_sp = plan.axes.get("sp", 1)
    spec = plan.spec("dp", "sp", "tp", None)
    body = functools.partial(ring_attention_local, axis_name="sp",
                             axis_size=n_sp, causal=causal,
                             kv_group=kv_group)
    return shard_map(body, mesh=plan.mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
