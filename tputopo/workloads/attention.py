"""Blockwise (flash) causal attention as a Pallas TPU kernel.

The flagship workload's hot op.  The einsum attention in model.py
materializes the full [B, N, S, S] score matrix in HBM — O(S^2) memory
traffic.  This kernel streams K/V blocks through VMEM with the standard
online-softmax recurrence, keeping the working set at
O(block_q x block_kv), so long sequences stay HBM-bandwidth-friendly and
the matmuls stay MXU-shaped (block sizes default to 128, the MXU tile).

Grid: (batch*heads, q_blocks, kv_blocks), sequential on TPU; the running
max/denominator/accumulator live in VMEM scratch that persists across the
kv_block steps of one q_block (initialized at kv==0, flushed at the last
kv step).  Causal blocks above the diagonal are predicated off entirely
(`@pl.when`), halving the work.

Used by model.forward when ``ModelConfig.attn_impl`` resolves to flash
(auto: TPU platform + divisible shapes); tests run the same kernel in
Pallas interpret mode on CPU against the einsum reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, n_kv: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = (ik <= iq) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale          # (bq, H)
        k = k_ref[0].astype(jnp.float32)                  # (bkv, H)
        v = v_ref[0].astype(jnp.float32)                  # (bkv, H)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq, bkv)
        if causal:
            bq = q_ref.shape[1]
            bkv = k_ref.shape[1]
            q_pos = iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bkv), 0)
            k_pos = ik * bkv + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bkv), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_prev = m_ref[:, :1]                             # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                            # (bq, bkv)
        alpha = jnp.exp(m_prev - m_new)                   # (bq, 1)
        l_ref[:, :1] = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, :1] = m_new

    @pl.when(ik == n_kv - 1)
    def _flush():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_kv",
                                             "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, block_q: int = 128,
                    block_kv: int = 128, interpret: bool = False) -> jax.Array:
    """q/k/v: [B, S, N, H] (same head count — expand GQA groups first, as
    model.py does).  Returns [B, S, N, H] in q's dtype.

    Differentiable: the forward pass is the Pallas kernel; the backward
    pass rematerializes attention through the einsum reference (nothing
    O(S^2) is saved between passes — the S^2 scores exist only transiently
    inside whichever pass is running).  A dedicated Pallas backward kernel
    is a further optimization, not a correctness need.
    """
    return _flash_vjp(q, k, v, causal, block_q, block_kv, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_vjp(q, k, v, causal, block_q, block_kv, interpret):
    return _flash_forward(q, k, v, causal=causal, block_q=block_q,
                          block_kv=block_kv, interpret=interpret)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_kv, interpret):
    out = _flash_forward(q, k, v, causal=causal, block_q=block_q,
                         block_kv=block_kv, interpret=interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_kv, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: reference_attention(a, b, c,
                                                         causal=causal),
                     q, k, v)
    return vjp(g)


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   causal: bool, block_q: int, block_kv: int,
                   interpret: bool) -> jax.Array:
    B, S, N, H = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    block_q = min(block_q, S)
    block_kv = min(block_kv, S)
    if S % block_q or S % block_kv:
        raise ValueError(f"seq len {S} not divisible by blocks "
                         f"({block_q}, {block_kv})")
    if causal and block_q != block_kv:
        raise ValueError("causal path requires block_q == block_kv")
    scale = 1.0 / (H ** 0.5)

    # [B, S, N, H] -> [B*N, S, H]: one grid row per (batch, head).
    def to_heads(x):
        return x.transpose(0, 2, 1, 3).reshape(B * N, S, H)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    n_q = S // block_q
    n_kv = S // block_kv

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          n_kv=n_kv),
        grid=(B * N, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, H), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, block_kv, H), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, block_kv, H), lambda b, iq, ik: (b, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, H), lambda b, iq, ik: (b, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B * N, S, H), q.dtype),
        scratch_shapes=[
            pltpu_vmem((block_q, 128), jnp.float32),  # running max (col 0)
            pltpu_vmem((block_q, 128), jnp.float32),  # running denom (col 0)
            pltpu_vmem((block_q, H), jnp.float32),    # accumulator
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return out.reshape(B, N, S, H).transpose(0, 2, 1, 3)


def pltpu_vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def reference_attention(q, k, v, *, causal: bool = True) -> jax.Array:
    """Einsum reference (the model.py path), for kernel verification."""
    B, S, N, H = q.shape
    scale = 1.0 / (H ** 0.5)
    logits = jnp.einsum("bqnh,bknh->bnqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        logits = jnp.where(k_pos <= q_pos, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bnqk,bknh->bqnh", probs,
                      v.astype(jnp.float32)).astype(q.dtype)
