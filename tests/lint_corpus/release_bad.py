# lint-corpus-relpath: tputopo/corpus/release_bad.py
"""KNOWN-BAD release-on-all-paths corpus."""

import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.budget = 3

    def leaky_span(self, span, risky):
        span.__enter__()
        risky()  # raises -> exits without __exit__
        span.__exit__(None, None, None)

    def leaky_acquire(self, risky):
        self._lock.acquire()
        risky()  # raises -> the release below never runs
        self._lock.release()

    def early_return_leak(self, span, flag):
        span.__enter__()
        if flag:
            return None  # BAD: returns without __exit__
        span.__exit__(None, None, None)
        return True

    def clobbered_budget(self, risky):
        saved = self.budget
        self.budget = 99
        risky()  # raises -> the restore below never runs
        self.budget = saved
