"""The ``release-on-all-paths`` checker: paired obligations must close.

Three acquisition idioms in this codebase create an obligation the
function must discharge on EVERY path out — including the exception
edges the CFG models:

- a **manual lock acquire** — ``self._lock.acquire()`` must reach
  ``self._lock.release()``;
- a **manual span/context enter** — ``span.__enter__()`` must reach
  ``span.__exit__(...)`` (the flight recorder's phase spans; the bind
  verb's publish section used exactly this shape);
- a **saved-and-overwritten attribute** — the retry/backfill-budget
  pattern ``saved = self.X; ...; self.X = <other>; ...; self.X = saved``
  must restore on all paths (the sim engine's terminal drain does this
  around ``max_backfill_failures``).

For each obligation-opening node, the rule asks the CFG: is the
function exit reachable without passing a closing node?  Exception
edges make the interesting cases real — a call that can raise between
``__enter__`` and ``__exit__`` leaks the span even though the straight-
line code looks paired.  The fix the finding prescribes is structural:
use ``with`` (the CFG's ``with_exit`` node closes on every path by
construction) or ``try``/``finally``.

Scoped to ``tputopo/`` — test fixtures deliberately exercise unbalanced
shapes.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tputopo.lint.callgraph import graph_for
from tputopo.lint.cfg import CFG, CFGNode, cfg_for, walk_exprs
from tputopo.lint.core import Checker, Finding, Module, dotted_name

#: acquire-method -> the method that discharges it.
_PAIRS = {"acquire": "release", "__enter__": "__exit__"}


def _call_on_base(node: ast.AST, methods) -> tuple[str, str] | None:
    """``(dotted base, method)`` when ``node`` is ``<base>.<m>(...)``
    with ``m`` in ``methods`` and a static dotted base."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in methods:
        base = dotted_name(node.func.value)
        if base is not None:
            return base, node.func.attr
    return None


class _Obligation:
    __slots__ = ("open_node", "ast_node", "describe", "closes")

    def __init__(self, open_node: CFGNode, ast_node: ast.AST,
                 describe: str, closes) -> None:
        self.open_node = open_node
        self.ast_node = ast_node
        self.describe = describe
        self.closes = closes  # predicate: CFGNode -> bool


def _node_asts(node: CFGNode):
    return walk_exprs(node)


class ReleasePathsChecker(Checker):
    rule = "release-on-all-paths"
    description = ("manually acquired locks (.acquire()), manually "
                   "entered spans (.__enter__()), and saved-then-"
                   "overwritten attributes (retry budgets) must be "
                   "released/restored on every CFG path out, exception "
                   "edges included — use `with` or try/finally")

    version = 1

    def __init__(self) -> None:
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        graph = graph_for(mods)
        by_path = {m.relpath: m for m in mods}
        restore_mods = self._modules_with_restore_shapes(mods)
        for fn in sorted(graph.functions.values(), key=lambda f: f.key):
            if not fn.relpath.startswith("tputopo/"):
                continue
            mod = by_path.get(fn.relpath)
            if mod is None:
                continue
            has_manual = ".acquire(" in mod.source \
                or ".__enter__(" in mod.source
            has_restore = (self._save_restore_candidates(fn)
                           if fn.relpath in restore_mods else {})
            if not has_manual and not has_restore:
                continue
            cfg = cfg_for(fn)
            obligations = []
            if has_manual:
                obligations += self._manual_obligations(cfg)
            obligations += self._restore_obligations(cfg, has_restore)
            for ob in obligations:
                if cfg.reachable_without(ob.open_node, ob.closes):
                    yield Finding(
                        fn.relpath, ob.ast_node.lineno,
                        ob.ast_node.col_offset, self.rule,
                        f"{ob.describe} is not released/restored on "
                        "every path out of "
                        f"{fn.qualname}() (exception edges included) — "
                        "use `with`, or wrap the span in try/finally")

    # ---- manual acquire/enter ---------------------------------------------

    def _manual_obligations(self, cfg: CFG) -> list[_Obligation]:
        out = []
        for node in cfg.nodes:
            for sub in _node_asts(node):
                got = _call_on_base(sub, _PAIRS)
                if got is None:
                    continue
                base, meth = got
                closer = _PAIRS[meth]

                def closes(n, base=base, closer=closer):
                    for s in _node_asts(n):
                        c = _call_on_base(s, {closer})
                        if c is not None and c[0] == base:
                            return True
                    return False

                out.append(_Obligation(
                    node, sub,
                    f"manual `{base}.{meth}()`", closes))
        return out

    # ---- saved-attribute restore (retry budgets) ---------------------------

    @staticmethod
    def _self_attr_of(expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        return None

    def _modules_with_restore_shapes(self, mods) -> set[str]:
        """Modules holding BOTH a ``name = self.attr`` save and a
        ``self.attr = name`` restore for the same attr *somewhere* —
        one pass over the cached node lists; the per-function scan runs
        only inside these (most modules have neither shape paired)."""
        out = set()
        for mod in mods:
            if not mod.relpath.startswith("tputopo/"):
                continue
            saves: dict[str, set[str]] = {}
            restores: dict[str, set[str]] = {}
            for node in mod.nodes():
                if not isinstance(node, ast.Assign) \
                        or len(node.targets) != 1:
                    continue
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    attr = self._self_attr_of(node.value)
                    if attr is not None:
                        saves.setdefault(attr, set()).add(t.id)
                else:
                    attr = self._self_attr_of(t)
                    if attr is not None and isinstance(node.value, ast.Name):
                        restores.setdefault(attr, set()).add(node.value.id)
            if any(saves.get(a, set()) & restores.get(a, set())
                   for a in saves):
                out.add(mod.relpath)
        return out

    def _save_restore_candidates(self, fn) -> dict[str, set[str]]:
        """{attr: {local names that saved it}} for attributes with BOTH
        a ``local = self.attr`` save and a ``self.attr = local`` restore
        somewhere in the function — the only shape that creates a
        restore obligation."""
        saves: dict[str, set[str]] = {}
        restores: dict[str, set[str]] = {}
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if isinstance(t, ast.Name):
                attr = self._self_attr_of(node.value)
                if attr is not None:
                    saves.setdefault(attr, set()).add(t.id)
            else:
                attr = self._self_attr_of(t)
                if attr is not None and isinstance(node.value, ast.Name):
                    restores.setdefault(attr, set()).add(node.value.id)
        return {attr: names & restores.get(attr, set())
                for attr, names in saves.items()
                if names & restores.get(attr, set())}

    def _restore_obligations(self, cfg: CFG,
                             candidates: dict[str, set[str]]
                             ) -> list[_Obligation]:
        """The obligation opens at an OVERWRITE of a saved attribute
        (``self.X = <something other than the saved name>``) and closes
        at any restore (``self.X = saved_name``) — but ONLY at
        overwrites the save actually dominates: a must-saved dataflow
        gates it, so an unrelated ``self.X = 1`` on a branch that never
        saved is not an obligation (review-verified false positive)."""
        out = []
        if not candidates:
            return out
        checker = self

        class _MustSaved:
            """fact: frozenset of attrs saved on EVERY path in."""

            def entry_fact(self):
                return frozenset()

            def join(self, a, b):
                return a & b

            def transfer(self, node, fact):
                s = node.stmt
                if node.kind == "stmt" and isinstance(s, ast.Assign) \
                        and len(s.targets) == 1 \
                        and isinstance(s.targets[0], ast.Name):
                    attr = checker._self_attr_of(s.value)
                    if attr in candidates \
                            and s.targets[0].id in candidates[attr]:
                        return fact | {attr}
                return fact

        from tputopo.lint.dataflow import run_forward

        saved_in = run_forward(cfg, _MustSaved())
        for node in cfg.nodes:
            s = node.stmt
            if node.kind != "stmt" or not isinstance(s, ast.Assign) \
                    or len(s.targets) != 1:
                continue
            attr = self._self_attr_of(s.targets[0])
            if attr not in candidates:
                continue
            if attr not in saved_in.get(node.idx, frozenset()):
                continue  # no save on (all) paths here — not the pattern
            names = candidates[attr]
            if isinstance(s.value, ast.Name) and s.value.id in names:
                continue  # this IS the restore
            if self._self_attr_of(s.value) == attr:
                continue  # self.X = self.X — the save shape, not a clobber

            def closes(n, attr=attr, names=names):
                st = n.stmt
                return (n.kind == "stmt" and isinstance(st, ast.Assign)
                        and len(st.targets) == 1
                        and self._self_attr_of(st.targets[0]) == attr
                        and isinstance(st.value, ast.Name)
                        and st.value.id in names)

            out.append(_Obligation(
                node, s,
                f"saved attribute `self.{attr}` (overwritten here)",
                closes))
        return out
