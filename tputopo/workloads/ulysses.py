"""All-to-all (Ulysses-style) sequence parallelism over the ``sp`` axis.

The second context-parallel strategy next to :mod:`tputopo.workloads.ring`
(same global layout contract, swappable via ``ModelConfig.sp_impl``).
Where ring attention rotates K/V chunks ``n_sp - 1`` times per layer
(`ppermute` over ICI neighbor links), the a2a strategy re-shards ONCE each
way: an `all_to_all` converts the sequence sharding into a *head*
sharding — every device then holds the FULL sequence for ``N / (tp*sp)``
heads — runs plain (flash) attention locally with no cross-device
bookkeeping, and a second `all_to_all` restores the sequence sharding.

Trade-off (the reason both strategies ship): a2a moves the whole Q/K/V/O
payload twice per layer but in two dense collectives XLA can schedule
wide across the torus, and its local compute is one full-sequence flash
call (best MXU shape).  Ring keeps peak activation memory at
O(S / n_sp) — a2a's local K/V is O(S) for its head shard — and rides
strictly neighbor links, so it wins at very long context or when heads
are too few to split (a2a needs ``sp`` to divide the local head count;
GQA K/V heads included).  Heuristic: a2a for throughput at moderate S
with plenty of heads, ring for maximum context length.

No counterpart in the reference (its design leaves model-internal
parallelism entirely to the workload, design.md:17-19 / SURVEY.md §1 L5);
the pattern follows the public DeepSpeed-Ulysses / JAX shard_map
literature, implemented here against the same placement invariant the
scheduler guarantees (a contiguous slice whose mesh axes ride ICI).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax import shard_map

from tputopo.workloads.attention import flash_attention, reference_attention


def _flash_block(S: int) -> int:
    """The block size the local flash call will actually use: prefer the
    kernel's full-size blocks, fall back to the largest divisor (the same
    chain as model._flash_dispatch — the gate MUST probe with the block it
    passes, or valid sequence lengths crash in _validate)."""
    for b in (512, 256):
        if S % b == 0:
            return b
    return min(128, S)


def _flash_shapes_ok(S: int) -> bool:
    block = _flash_block(S)
    return S >= 16 and S % block == 0 and block % 8 == 0


def a2a_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        axis_name: str, axis_size: int, causal: bool = True,
                        kv_group: int = 1, impl: str = "einsum",
                        interpret: bool = False) -> jax.Array:
    """Per-device body (call under shard_map): q [B, Sc, Nl, H], k/v
    [B, Sc, Nl/kv_group, H] local chunks; returns local [B, Sc, Nl, H]
    as if attention ran over the full global sequence.

    Requires ``Nl % axis_size == 0`` and ``(Nl/kv_group) % axis_size == 0``
    (checked by the global wrapper): the all_to_all splits the head axis
    into ``axis_size`` groups while concatenating the sequence axis.
    """
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    # [B, Sc, Nl, H] -> [B, S, Nl/sp, H]: heads scatter, sequence gathers.
    qg = a2a(q, split_axis=2, concat_axis=1)
    kg = a2a(k, split_axis=2, concat_axis=1)
    vg = a2a(v, split_axis=2, concat_axis=1)
    if kv_group > 1:
        kg = jnp.repeat(kg, kv_group, axis=2)
        vg = jnp.repeat(vg, kv_group, axis=2)
    if impl == "flash":
        blk = _flash_block(qg.shape[1])
        out = flash_attention(qg, kg, vg, causal=causal, block_q=blk,
                              block_kv=blk, interpret=interpret)
    else:
        out = reference_attention(qg, kg, vg, causal=causal)
    # [B, S, Nl/sp, H] -> [B, Sc, Nl, H]: sequence scatters back, heads gather.
    return a2a(out, split_axis=1, concat_axis=2)


def a2a_attention(q: jax.Array, k: jax.Array, v: jax.Array, plan, *,
                  causal: bool = True, kv_group: int = 1,
                  impl: str = "auto") -> jax.Array:
    """Global-array entry, same contract as :func:`ring.ring_attention`:
    q [B, S, N, H] (k/v may carry N/kv_group heads), logically global,
    laid out batch-over-dp, seq-over-sp, heads-over-tp on ``plan``'s mesh.

    ``impl``: "flash" runs the Pallas kernel on the full-sequence local
    block (interpret mode off-TPU), "einsum" the reference block, "auto"
    picks flash on TPU whenever the global sequence shape allows it.
    """
    n_sp = plan.axes.get("sp", 1)
    n_tp = plan.axes.get("tp", 1)
    B, S, N, _ = q.shape
    n_local = N // n_tp
    nkv_local = k.shape[2] // n_tp
    if n_local % n_sp or nkv_local % n_sp:
        raise ValueError(
            f"a2a sequence parallelism needs sp={n_sp} to divide the local "
            f"head counts (q {n_local}, kv {nkv_local}); expand GQA heads "
            "or use the ring strategy")
    if impl == "auto":
        impl = ("flash" if jax.default_backend() == "tpu"
                and _flash_shapes_ok(S) else "einsum")
    body = functools.partial(
        a2a_attention_local, axis_name="sp", axis_size=n_sp, causal=causal,
        kv_group=kv_group, impl=impl,
        interpret=jax.default_backend() != "tpu")
    from tputopo.workloads.sharding import shard_map_kwargs

    spec = plan.spec("dp", "sp", "tp", None)
    return shard_map(body, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False,
                     **shard_map_kwargs(plan, {"dp", "sp", "tp"}))(q, k, v)
