"""Sharding-aware checkpoint/resume for the training workload (orbax).

The scheduler side needs no checkpointing — its durable state lives in
K8s object metadata (the reference's statelessness posture, SURVEY.md
§5.4).  The *workload* side does: a gang member preempted by the TTL GC
or a node failure must resume training rather than restart (the
elastic-recovery expectation a placement framework's users have).

Orbax handles the sharded TrainState natively: each host saves only its
addressable shards, and restore redistributes onto the current MeshPlan
— which may be a *different* slice than the one that saved, because the
extender may re-place the gang elsewhere on the torus.  That re-place-
and-resume flow is exactly what the two-phase handshake + GC enable.
"""

from __future__ import annotations

from pathlib import Path

import jax
import orbax.checkpoint as ocp

from tputopo.workloads.train import TrainState


def save(ckpt_dir: str | Path, state: TrainState) -> int:
    """Write one step's checkpoint; returns the step number saved."""
    step = int(state.step)
    path = Path(ckpt_dir).absolute() / f"step_{step}"
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state)
    return step


def latest_step(ckpt_dir: str | Path) -> int | None:
    root = Path(ckpt_dir)
    if not root.is_dir():
        return None
    steps = []
    for p in root.iterdir():
        if p.name.startswith("step_"):
            try:
                steps.append(int(p.name[len("step_"):]))
            except ValueError:
                continue
    return max(steps) if steps else None


def restore(ckpt_dir: str | Path, target: TrainState,
            step: int | None = None) -> TrainState | None:
    """Restore the latest (or given) step into ``target``'s sharded layout.

    ``target`` supplies structure AND shardings (an abstract or concrete
    TrainState built on the *current* mesh), so a checkpoint written on a
    different slice lands correctly redistributed.  Returns None when the
    directory holds no checkpoint (fresh start).
    """
    if step is None:
        step = latest_step(ckpt_dir)
    if step is None:
        return None
    path = Path(ckpt_dir).absolute() / f"step_{step}"
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, abstract)
