# lint-corpus-relpath: tputopo/sim/report.py
"""Corrected schema-additivity corpus: every emitted key is pinned, the
gated key is emitted only when its feature ran, and every version string
is a contract constant."""

SCHEMA = "tputopo.sim/v2"
SCHEMA_NEXT = "tputopo.sim/v9"

SCHEMA_KEY_MANIFEST = {
    "tputopo.sim/v2": {
        "top": ("schema", "policies"),
        "top_gated": ("throughput",),
        "policy": ("jobs",),
    },
    "tputopo.sim/v9": {"policy_gated": ("replicas",)},
}


def build_report(policies, throughput=None):
    out = {
        "schema": SCHEMA,
        "policies": policies,
    }
    if throughput is not None:
        out["throughput"] = dict(throughput)
    return out


class MetricsCollector:
    def report(self, replicas=None):
        out = {"jobs": 0}
        if replicas is not None:
            out["replicas"] = replicas
        return out
