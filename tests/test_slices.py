"""Selector/allocator tests — the rebuild analog of Gaia's Exp.1-4
correctness runs (Gaia PDF §IV Tables I-IV; SURVEY.md §4): deterministic
repetition, staged occupancy fixtures, and zero invalid choices."""

import pytest

from tputopo.topology import Allocator, ChipTopology, enumerate_shapes
from tputopo.topology.slices import box_chips, enumerate_placements


def v5p32():
    """The BASELINE.json target: v5p-32 == 16 chips as a 2x2x4 box."""
    return ChipTopology.build("v5p", (2, 2, 4))


def test_shape_enumeration_prefers_compact():
    t = v5p32()
    shapes = enumerate_shapes(t, 8)
    assert shapes[0].dims == (2, 2, 2)  # most bandwidth for 8 chips
    assert all(s.num_chips == 8 for s in shapes)
    shapes4 = enumerate_shapes(t, 4)
    assert shapes4[0].dims in ((2, 2, 1), (1, 2, 2), (2, 1, 2))


def test_placement_enumeration_respects_occupancy():
    t = v5p32()
    alloc = Allocator(t)
    shape = enumerate_shapes(t, 8)[0]
    free_all = enumerate_placements(t, shape, alloc.free)
    assert len(free_all) == 3  # 2x2x2 slides along z only: offsets 0,1,2
    alloc.mark_used([(0, 0, 0)])
    fewer = enumerate_placements(t, shape, alloc.free)
    assert len(fewer) == 2


def test_allocate_full_slice():
    t = v5p32()
    alloc = Allocator(t)
    p = alloc.allocate(16)
    assert p is not None and p.is_contiguous_box
    assert p.dims == (2, 2, 4)
    assert len(alloc.free) == 0
    assert alloc.allocate(1) is None  # exhausted


def test_deterministic_repetition_like_gaia_exp1():
    # Gaia Exp.1: 500 repetitions, invalid choices must be zero
    # (PDF §IV Table I).  Ours is deterministic: identical every time.
    results = set()
    for _ in range(100):
        alloc = Allocator(v5p32())
        p = alloc.allocate(8)
        results.add(p.chips)
    assert len(results) == 1
    chips = next(iter(results))
    assert len(chips) == 8


def test_singular_anti_fragmentation():
    # Gaia Exp.3 analog (PDF Alg.3, Table III): a 1-chip request must not
    # break up a pristine region when a tighter spot exists.
    t = v5p32()
    alloc = Allocator(t)
    # Occupy the z=0 plane except one chip: that hole is the tight spot.
    alloc.mark_used([(0, 0, 0), (0, 1, 0), (1, 0, 0)])
    p = alloc.allocate(1)
    assert p.chips == ((1, 1, 0),)  # fills the hole, not the open region


def test_pair_request_prefers_adjacent():
    # Gaia Exp.4 analog (PDF Alg.4, Table IV) / BASELINE config 2.
    t = v5p32()
    alloc = Allocator(t)
    p = alloc.allocate(2)
    assert p is not None
    a, b = p.chips
    assert t.hop_distance(a, b) == 1


def test_gang_4x4_disjoint_contiguous():
    # BASELINE config 4: gang-schedule 4 x (4-chip) DP replicas on v5p-32.
    t = v5p32()
    alloc = Allocator(t)
    gang = alloc.allocate_gang(4, 4)
    assert gang is not None and len(gang) == 4
    seen = set()
    for p in gang:
        assert p.is_contiguous_box
        assert len(p.chips) == 4
        assert not (seen & set(p.chips))  # disjoint
        seen.update(p.chips)
    assert len(seen) == 16  # tiles the whole slice


def test_gang_all_or_nothing():
    t = v5p32()
    alloc = Allocator(t)
    alloc.mark_used(box_chips(t, (0, 0, 0), (2, 2, 1)))  # 4 chips gone
    assert alloc.find_gang(4, 4) is None  # only 12 chips left
    assert len(alloc.free) == 12  # nothing was consumed by the failed gang
    gang = alloc.allocate_gang(3, 4)
    assert gang is not None


def test_blob_fallback_for_non_box_k():
    # k=7 admits no box in 2x2x4; fallback must return a *connected* set.
    t = v5p32()
    alloc = Allocator(t)
    p = alloc.allocate(7)
    assert p is not None and len(p.chips) == 7
    assert not p.is_contiguous_box
    # connectivity check
    chips = set(p.chips)
    frontier = [next(iter(chips))]
    seen = {frontier[0]}
    while frontier:
        c = frontier.pop()
        for n in t.neighbors(c):
            if n in chips and n not in seen:
                seen.add(n)
                frontier.append(n)
    assert seen == chips


def test_packing_survives_fragmentation_pressure():
    # SURVEY.md §7 hard part 1: allocate/release churn must keep a 2x2x2
    # request satisfiable when 8 chips are free.
    t = v5p32()
    alloc = Allocator(t)
    p1 = alloc.allocate(4)
    p2 = alloc.allocate(2)
    p3 = alloc.allocate(2)
    assert len(alloc.free) == 8
    p = alloc.find(8)
    assert p is not None, "anti-fragmentation packing should leave a free 8-box"
    assert p.is_contiguous_box


def test_largest_free_box_metric():
    t = v5p32()
    alloc = Allocator(t)
    vol, dims = alloc.largest_free_box()
    assert vol == 16
    alloc.allocate(8)
    vol2, dims2 = alloc.largest_free_box()
    assert vol2 == 8


def test_release_returns_capacity():
    t = v5p32()
    alloc = Allocator(t)
    p = alloc.allocate(16)
    assert alloc.find(1) is None
    alloc.release(p.chips)
    assert alloc.allocate(16) is not None


def test_invalid_requests():
    alloc = Allocator(v5p32())
    with pytest.raises(ValueError):
        alloc.find(0)
    assert alloc.find(17) is None


def test_largest_free_box_matches_bruteforce():
    """VERDICT r1 #9: the sliding-window rewrite must agree with the shape
    x origin definition on random occupancy states."""
    import random

    from tputopo.topology.slices import enumerate_placements, enumerate_shapes

    rng = random.Random(7)
    topo = ChipTopology.build("v5p", (2, 2, 4))
    for trial in range(12):
        alloc = Allocator(topo)
        used = rng.sample(list(topo.chips), rng.randrange(0, 15))
        alloc.mark_used(used)
        got = alloc.largest_free_box()
        free = alloc.free
        want = None
        for k in range(len(free), 0, -1):
            for shape in enumerate_shapes(topo, k, alloc.cost):
                if enumerate_placements(topo, shape, free, alloc.cost):
                    want = (k, shape.dims)
                    break
            if want:
                break
        assert got == want, (trial, sorted(used), got, want)


def test_largest_free_box_bounded_on_256_chip_torus():
    """VERDICT r1 #9: /state's fragmentation metric must stay cheap on a
    16x16 v5e (256 chips) — the old volume-descending rescan did unbounded
    shape x origin work per hit."""
    import time

    topo = ChipTopology.build("v5e", (16, 16))
    alloc = Allocator(topo)
    # Fragment it: checkerboard 2x2 blocks used.
    used = [c for c in topo.chips if (c[0] // 2 + c[1] // 2) % 2 == 0]
    alloc.mark_used(used)
    t0 = time.perf_counter()
    vol, dims = alloc.largest_free_box()
    elapsed = time.perf_counter() - t0
    assert vol == 4 and sorted(dims) == [2, 2]
    # Absolute-time gate policy (VERDICT r3 #8): typical elapsed is a few
    # ms; the 1 s bound only guards against a complexity regression (the
    # former shape x origin rescan was unbounded) with ~100x headroom for
    # shared-host variance.
    assert elapsed < 1.0, f"largest_free_box took {elapsed:.2f}s"


def test_mask_geometry_matches_set_semantics():
    """The bitmask fast path (box_mask/free_mask/neighbor popcount) must be
    observationally identical to the set-based definitions on random
    occupancy states."""
    import random

    from tputopo.topology import parse_topology
    from tputopo.topology.slices import (
        Allocator, _boxes_for, _free_boundary, box_chips, chips_mask,
        enumerate_placements, enumerate_shapes,
    )

    topo = parse_topology("v5p:4x4x4")
    rng = random.Random(7)
    chips = list(topo.chips)
    for trial in range(20):
        used = set(rng.sample(chips, rng.randint(0, 48)))
        free = frozenset(c for c in chips if c not in used)
        fmask = chips_mask(topo, free)
        for k in (2, 4, 8):
            for shape in enumerate_shapes(topo, k, Allocator(topo).cost):
                placements = enumerate_placements(topo, shape, free)
                # set-based reference for the same shape
                ref = []
                for o, bchips, mask, nbr in _boxes_for(topo, shape.dims):
                    assert bchips == box_chips(topo, o, shape.dims)
                    feasible_ref = all(c in free for c in bchips)
                    assert feasible_ref == (mask & fmask == mask), (o, shape)
                    if feasible_ref:
                        ref.append(bchips)
                        assert (nbr & fmask).bit_count() == _free_boundary(
                            topo, frozenset(bchips), free)
                assert [p.chips for p in placements] == ref


def test_find_within_hint_is_result_identical():
    """The ``within`` performance hint (the per-node candidate pruning the
    sort hot loop uses) must never change the result — including when the
    hint does not actually cover the free set (it is then ignored)."""
    import random

    t = ChipTopology.build("v5p", (4, 4, 4))
    rng = random.Random(7)
    hosts = list(t.hosts.values())
    for trial in range(40):
        host_chips = tuple(rng.choice(hosts))
        n_free = rng.randint(0, len(host_chips))
        free = frozenset(rng.sample(list(host_chips), n_free))
        alloc = Allocator(t)
        for k in (1, 2, 3, 4):
            base = alloc.find(k, free)
            hinted = alloc.find(k, free, within=host_chips)
            assert base == hinted, (trial, k, sorted(free))
        # A hint that does NOT cover the free set must be ignored, not
        # corrupt the search.
        wide_free = free | {c for c in t.chips if c not in host_chips and rng.random() < 0.1}
        for k in (2, 4):
            assert alloc.find(k, frozenset(wide_free)) == \
                alloc.find(k, frozenset(wide_free), within=host_chips)


def test_free_cache_tracks_mutations():
    t = v5p32()
    a = Allocator(t)
    assert len(a.free) == 16
    a.mark_used([(0, 0, 0), (0, 0, 1)])
    assert len(a.free) == 14 and (0, 0, 0) not in a.free
    a.release([(0, 0, 0)])
    assert (0, 0, 0) in a.free and len(a.free) == 15
    b = a.clone()
    b.mark_used([(0, 0, 0)])
    assert (0, 0, 0) in a.free and (0, 0, 0) not in b.free, \
        "clone must not share occupancy with its source"


def test_incremental_largest_free_box_matches_scan_oracle():
    """Satellite: the incremental largest-free-box index (witness box +
    rank-bounded rescan) must equal the windowed-cumsum oracle after EVERY
    step of randomized mark/release sequences, on wrapped, partially
    wrapped, and open toruses (seam-crossing boxes included)."""
    import random

    cases = [
        ("v5p", (4, 4, 4), None),                    # fully wrapped torus
        ("v5p", (2, 2, 4), None),                    # partially wrapped
        ("v5p", (4, 4, 4), (False, False, False)),   # open box
        ("v5e", (8, 4), (True, False)),              # mixed-wrap 2D
    ]
    for gen, dims, wrap in cases:
        topo = ChipTopology.build(gen, dims, wrap)
        alloc = Allocator(topo)
        rng = random.Random(42)
        for step in range(200):
            free, used = list(alloc.free), list(alloc.used)
            if used and (not free or rng.random() < 0.45):
                alloc.release(rng.sample(
                    used, rng.randrange(1, min(6, len(used)) + 1)))
            else:
                alloc.mark_used(rng.sample(
                    free, rng.randrange(1, min(6, len(free)) + 1)))
            got = alloc.largest_free_box()
            want = alloc.largest_free_box_scan()
            assert got == want, (gen, dims, wrap, step, got, want)


def test_largest_free_box_seam_crossing_incremental():
    """A free region that only forms a box ACROSS the wrap seam: both the
    incremental index and the oracle must see the 4x4 box spanning
    x in {6,7,0,1}."""
    topo = ChipTopology.build("v5e", (8, 4), (True, False))
    alloc = Allocator(topo)
    alloc.mark_used([c for c in topo.chips if 2 <= c[0] <= 5])
    got = alloc.largest_free_box()
    assert got == alloc.largest_free_box_scan()
    assert got is not None and got[0] == 16  # the seam-crossing 4x4
    # Releasing one strip grows the box incrementally (release path).
    alloc.release([c for c in topo.chips if c[0] == 2])
    got = alloc.largest_free_box()
    assert got == alloc.largest_free_box_scan()
    assert got[0] == 20


def test_largest_free_box_incremental_survives_clone():
    """clone() shares the index snapshot; diverging the clone's occupancy
    must not corrupt either side's metric."""
    topo = ChipTopology.build("v5p", (2, 2, 4))
    a = Allocator(topo)
    a.mark_used(list(topo.chips)[:4])
    assert a.largest_free_box() == a.largest_free_box_scan()
    b = a.clone()
    b.mark_used(list(b.free)[:3])
    assert b.largest_free_box() == b.largest_free_box_scan()
    a.release(list(a.used)[:2])
    assert a.largest_free_box() == a.largest_free_box_scan()
