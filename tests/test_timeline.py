"""Fleet-gauge timeline (tputopo.obs.timeline, PR 19): the bounded
byte-deterministic trajectory recorder, its power-of-two compaction, the
schema-v9 sim report block behind the registered ``SimEngine.TIMELINE``
kill switch, and the live extender surface.

The load-bearing contracts:

- the recorder is EXACT below the point budget (stride 1, every sample
  emitted) and bounded at any scale (a 40k-sample stream emits <= the
  pinned budget), deterministically — same stream, same bytes;
- ``--timeline`` off — flag absent OR switch off — keeps the report
  byte-identical to the v8 shapes across the standing config matrix
  (plain / defrag / chaos / preempt-mixed / replicas / batch), and the
  on-path is pure addition (strip the timeline keys, recover the off
  bytes);
- sequential and ``--jobs 2`` timeline reports are byte-identical;
- the saturation analytics are computed from the raw stream, not the
  compacted buckets;
- the extender's ``/debug/timeline`` + Prometheus gauges serve the
  wall-clock recorder and stand down cleanly when disabled.
"""

from __future__ import annotations

import json
import urllib.request

import pytest

from tputopo.obs.timeline import (MARK_KINDS, POINT_BUDGET,
                                  TimelineRecorder, TimelineSampler,
                                  bucket_at)
from tputopo.sim.engine import SimEngine, run_trace
from tputopo.sim.trace import TraceConfig

SMALL = dict(nodes=16, arrivals=60)


def _canon(report: dict) -> str:
    """The determinism projection: everything but the two documented
    wall-clock blocks, as stable bytes."""
    r = dict(report)
    r.pop("throughput", None)
    r.pop("phase_wall", None)
    return json.dumps(r, sort_keys=True)


def _strip_timeline(report: dict, off_schema: str) -> dict:
    """Remove every timeline addition (schema marker, engine knob record,
    per-policy blocks, divergence annotations) — what remains must be the
    off-path report byte-for-byte (pure additivity)."""
    r = json.loads(json.dumps(report))
    r["schema"] = off_schema
    r["engine"].pop("timeline", None)
    for rec in r["policies"].values():
        rec.pop("timeline", None)
    for div in (r.get("ab", {}).get("first_divergence") or {}).values():
        if div:
            div.pop("timeline", None)
    return r


# ---- recorder unit behavior -------------------------------------------------


def test_recorder_exact_below_budget():
    rec = TimelineRecorder(budget=64)
    for i in range(50):
        rec.sample(float(i), 0.5, 0.1, 100, i % 7, 3)
    blk = rec.block()
    assert blk["stride"] == 1
    assert blk["points"] == 50 == blk["samples"]
    assert blk["t"] == [float(i) for i in range(50)]
    assert blk["queue_depth"] == [i % 7 for i in range(50)]


def test_recorder_bounded_at_40k_samples():
    rec = TimelineRecorder()
    for i in range(40_000):
        rec.sample(float(i), (i % 100) / 100.0, 0.2, 4096 - i % 64,
                   i % 30, i % 11)
    blk = rec.block()
    assert blk["samples"] == 40_000
    assert blk["points"] <= POINT_BUDGET
    assert blk["stride"] == 256  # 40000 / 256 -> next power of two
    # Columnar arrays stay aligned with the point count.
    for key in ("t", "util", "frag", "free_chips", "queue_depth",
                "running", "wm_skips"):
        assert len(blk[key]) == blk["points"], key
    for kind in MARK_KINDS:
        assert len(blk["marks"][kind]) == blk["points"]
    # Bucket end-times stay monotone through compaction.
    assert blk["t"] == sorted(blk["t"])


def test_recorder_deterministic_same_stream_same_bytes():
    def run() -> str:
        rec = TimelineRecorder(budget=32)
        for i in range(1000):
            if i % 37 == 0:
                rec.mark("conflict")
            if i % 101 == 0:
                rec.note_arrival(float(i))
            rec.sample(float(i), (i % 91) / 91.0, (i % 13) / 13.0,
                       512 - i % 128, i % 17, i % 5, i // 100)
        return json.dumps(rec.block(), sort_keys=True)

    assert run() == run()


def test_recorder_merge_semantics():
    # budget=2: after the third sealed point, pairs merge and stride
    # doubles — gauges keep the max, free the min, wm the last, marks sum.
    rec = TimelineRecorder(budget=2)
    rec.mark("conflict")
    rec.sample(1.0, 0.2, 0.1, 90, 4, 1, 0)
    rec.mark("conflict")
    rec.mark("preempt")
    rec.sample(2.0, 0.8, 0.3, 70, 2, 2, 5)
    blk = rec.block()
    assert blk["points"] == 1 and blk["stride"] == 2
    assert blk["t"] == [2.0]          # merged bucket keeps the END time
    assert blk["util"] == [0.8]       # max
    assert blk["frag"] == [0.3]       # max
    assert blk["free_chips"] == [70]  # min
    assert blk["queue_depth"] == [4]  # max
    assert blk["wm_skips"] == [5]     # cumulative tail
    assert blk["marks"]["conflict"] == [2]
    assert blk["marks"]["preempt"] == [1]
    assert blk["marks"]["defrag"] == [0]


def test_recorder_block_is_pure_read():
    rec = TimelineRecorder(budget=8)
    for i in range(100):
        rec.sample(float(i), 0.5, 0.0, 10, 0, 1)
    a = json.dumps(rec.block(), sort_keys=True)
    b = json.dumps(rec.block(), sort_keys=True)
    assert a == b
    rec.sample(100.0, 0.5, 0.0, 10, 0, 1)  # still accepts samples after


def test_recorder_saturation_analytics_exact():
    rec = TimelineRecorder(budget=4)  # aggressive compaction on purpose:
    # the analytics must come from the raw stream, not the buckets.
    rec.note_arrival(0.0)
    rec.sample(0.0, 0.5, 0.0, 10, 1, 0)
    rec.sample(10.0, 0.95, 0.0, 2, 3, 1)   # onset at t=10
    rec.note_arrival(12.0)                 # last arrival
    rec.sample(20.0, 0.95, 0.0, 2, 5, 1)   # peak queue 5 at t=20
    rec.sample(30.0, 0.5, 0.0, 10, 1, 2)   # 10+10 s spent >= 0.9
    rec.sample(40.0, 0.2, 0.0, 12, 0, 1)   # queue drains at t=40
    sat = rec.block()["saturation"]
    assert sat["onset_t"] == 10.0
    assert sat["peak_queue_depth"] == 5
    assert sat["peak_queue_t"] == 20.0
    assert sat["above_util_s"] == 20.0     # step-function integral
    assert sat["last_arrival_t"] == 12.0
    assert sat["drain_s"] == 28.0          # 40 - 12
    assert sat["util_threshold"] == 0.9


def test_recorder_drain_restarts_on_new_arrival():
    rec = TimelineRecorder()
    rec.note_arrival(0.0)
    rec.sample(5.0, 0.1, 0.0, 10, 0, 0)    # drained at t=5...
    rec.note_arrival(8.0)                  # ...but a new arrival resets it
    rec.sample(9.0, 0.1, 0.0, 10, 2, 0)
    assert rec.block()["saturation"]["drain_s"] is None
    rec.sample(11.0, 0.1, 0.0, 10, 0, 0)
    assert rec.block()["saturation"]["drain_s"] == 3.0


def test_recorder_tier_depths_presence_gated():
    rec = TimelineRecorder()
    rec.sample(0.0, 0.1, 0.0, 10, 1, 0)
    assert "tiers" not in rec.block()
    rec.sample(1.0, 0.1, 0.0, 10, 2, 0, tier_depths={"serving": 2})
    blk = rec.block()
    assert blk["tiers"]["serving"] == [0, 2]  # absent bucket = depth 0


def test_bucket_at_lookup():
    rec = TimelineRecorder()
    for i in range(10):
        rec.sample(float(i * 10), i / 10.0, 0.0, 100 - i, i, i)
    blk = rec.block()
    b = bucket_at(blk, 35.0)
    assert b["t"] == 40.0 and b["index"] == 4   # first bucket-end >= t
    assert bucket_at(blk, -5.0)["index"] == 0
    assert bucket_at(blk, 1e9)["index"] == blk["points"] - 1
    assert bucket_at({"t": []}, 1.0) is None


# ---- sim report integration -------------------------------------------------


def _run(timeline=False, jobs=1, **kw):
    cfg_kw = dict(SMALL)
    cfg_kw.update(kw.pop("cfg", {}))
    return run_trace(TraceConfig(seed=0, **cfg_kw), ["ici", "naive"],
                     timeline=timeline, jobs=jobs, **kw)


def test_sim_report_gains_v9_timeline_block():
    report = _run(timeline=True)
    assert report["schema"] == "tputopo.sim/v9"
    assert report["engine"]["timeline"] == {"points_budget": POINT_BUDGET}
    for rec in report["policies"].values():
        tl = rec["timeline"]
        assert tl["budget"] == POINT_BUDGET
        assert 0 < tl["points"] <= POINT_BUDGET
        assert tl["samples"] >= tl["points"]
        assert len(tl["t"]) == tl["points"]
        assert set(tl["marks"]) == set(MARK_KINDS)
        assert "saturation" in tl


def test_sim_timeline_divergence_buckets():
    report = _run(timeline=True)
    (div,) = report["ab"]["first_divergence"].values()
    assert div is not None
    tl = div["timeline"]
    for side in ("ici", "naive"):
        assert set(tl[side]) == {"index", "t", "util", "frag",
                                 "free_chips", "queue_depth", "running"}


def test_sim_timeline_jobs2_byte_identical():
    assert _canon(_run(timeline=True)) == _canon(_run(timeline=True, jobs=2))


#: The standing config matrix the off-path byte-identity contract covers.
MATRIX = {
    "plain": {},
    "defrag": {"defrag": {}},
    "chaos": {"chaos": "api-flake"},
    "preempt-mixed": {"preempt": {}, "cfg": {"workload": "mixed"}},
    "replicas": {"replicas": {"count": 2}},
    "batch": {"batch": {}},
}


@pytest.mark.parametrize("name", sorted(MATRIX))
def test_timeline_off_path_byte_identical(name, monkeypatch):
    off_rep = _run(**dict(MATRIX[name]))
    off = _canon(off_rep)
    # Flag on, switch OFF: the kill switch must make --timeline
    # byte-invisible.
    monkeypatch.setattr(SimEngine, "TIMELINE", False)
    assert _canon(_run(timeline=True, **dict(MATRIX[name]))) == off
    monkeypatch.setattr(SimEngine, "TIMELINE", True)
    # Flag on, switch on: stripping the timeline additions must recover
    # the off bytes exactly (pure additivity — nothing else moved).
    on = _run(timeline=True, **dict(MATRIX[name]))
    assert _canon(_strip_timeline(on, off_rep["schema"])) == off


def test_timeline_mixed_trace_carries_tier_depths():
    report = _run(timeline=True, preempt={}, cfg={"workload": "mixed"})
    tl = report["policies"]["ici"]["timeline"]
    assert "tiers" in tl
    assert set(tl["tiers"]) <= {"serving", "prod", "batch"}
    for series in tl["tiers"].values():
        assert len(series) == tl["points"]


# ---- extender live surface --------------------------------------------------


def _fake_clock():
    state = {"t": 1000.0}

    def clock():
        state["t"] += 1.0
        return state["t"]

    return clock


def test_sampler_feeds_recorder_and_counts():
    calls = []

    class M:
        def inc(self, name, n=1):
            calls.append(name)

    gauges = {"util": 0.5, "frag": 0.1, "free_chips": 64,
              "queue_depth": 2, "running": 3}
    s = TimelineSampler(lambda: dict(gauges), period_s=10.0,
                        clock=_fake_clock(), metrics=M())
    s.sample_once()
    s.sample_once()
    blk = s.block()
    assert blk["samples"] == 2
    assert s.last["util"] == 0.5 and s.last["t"] == 1002.0
    assert calls == ["timeline_samples", "timeline_samples"]
    assert s.errors == 0


def test_sampler_survives_gauge_failures():
    def boom():
        raise RuntimeError("api blip")

    s = TimelineSampler(boom, clock=_fake_clock())
    s.sample_once()
    assert s.errors == 1
    assert s.block()["samples"] == 0  # nothing recorded, nothing raised


@pytest.fixture
def extender_srv():
    from tests.cluster import build_cluster
    from tputopo.extender import (ExtenderConfig, ExtenderHTTPServer,
                                  ExtenderScheduler)

    api, _ = build_cluster()
    config = ExtenderConfig()
    sched = ExtenderScheduler(api, config)
    srv = ExtenderHTTPServer(sched, config, port=0).start()
    yield srv
    srv.stop()


def _get(srv, path: str) -> str:
    host, port = srv.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}") as r:
        return r.read().decode()


def test_debug_timeline_endpoint(extender_srv):
    out = json.loads(_get(extender_srv, "/debug/timeline"))
    assert out["enabled"] is True
    # start() seeds one sample before the thread's first period.
    assert out["timeline"]["samples"] >= 1
    assert out["last"] is not None
    assert out["errors"] == 0


def test_metrics_exports_timeline_gauges(extender_srv):
    text = _get(extender_srv, "/metrics")
    for g in ("util", "frag", "free_chips", "queue_depth", "running"):
        assert f"tputopo_extender_timeline_{g} " in text
    assert "tputopo_extender_timeline_samples_total" in text


def test_debug_timeline_disabled_stands_down():
    from tests.cluster import build_cluster
    from tputopo.extender import (ExtenderConfig, ExtenderHTTPServer,
                                  ExtenderScheduler)

    api, _ = build_cluster()
    config = ExtenderConfig(timeline_enabled=False)
    sched = ExtenderScheduler(api, config)
    srv = ExtenderHTTPServer(sched, config, port=0).start()
    try:
        out = json.loads(_get(srv, "/debug/timeline"))
        assert out == {"enabled": False, "timeline": None}
        assert "tputopo_extender_timeline_util" not in _get(srv, "/metrics")
    finally:
        srv.stop()


def test_config_roundtrip_with_timeline_knobs(tmp_path):
    from tputopo.extender import ExtenderConfig

    cfg = ExtenderConfig(timeline_enabled=False, timeline_period_s=2.5,
                         timeline_points=32)
    p = tmp_path / "cfg.json"
    cfg.save(p)
    back = ExtenderConfig.load(p)
    assert back.timeline_enabled is False
    assert back.timeline_period_s == 2.5
    assert back.timeline_points == 32
