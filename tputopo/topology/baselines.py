"""Baseline (topology-blind) allocation policies, for A/B comparison.

The reference proves its value by A/B against the stock kube-scheduler
(Gaia PDF §IV Exp.5/6: the default scheduler picks by count only, landing
jobs on scattered devices; Fig. 11 contrasts a scattered vs link-local
placement).  ``naive_pick`` reproduces that behavior for a TPU node: take
the k lowest-indexed free chips, ignoring geometry — exactly what a
count-only extended-resource scheduler plus the kubelet's arbitrary
device pick does.  Used by tests and bench to quantify the bandwidth and
fragmentation delta of topology awareness.
"""

from __future__ import annotations

from typing import Callable

from tputopo.topology.model import ChipTopology, Coord

# Registry of named baseline chip pickers, the pluggable half of an A/B
# study: every entry has the same signature (topo, free, k) -> chips|None,
# so the sim (tputopo.sim.policies) and tests can wire any of them against
# the ICI-aware scorer without knowing the policy by name.
BASELINE_PICKERS: dict[str, "Callable[[ChipTopology, frozenset, int], tuple | None]"] = {}


def register_picker(name: str):
    """Decorator: register a baseline chip picker under ``name``."""
    def deco(fn):
        BASELINE_PICKERS[name] = fn
        return fn
    return deco


def get_picker(name: str):
    try:
        return BASELINE_PICKERS[name]
    except KeyError:
        raise KeyError(
            f"unknown baseline picker {name!r}; registered: "
            f"{sorted(BASELINE_PICKERS)}") from None


@register_picker("naive")
def naive_pick(topo: ChipTopology, free: frozenset[Coord], k: int) -> tuple[Coord, ...] | None:
    """First-fit: the k lowest row-major-indexed free chips (count-only)."""
    if len(free) < k:
        return None
    ordered = sorted(free, key=topo.index)
    return tuple(ordered[:k])


@register_picker("spread")
def spread_pick(topo: ChipTopology, free: frozenset[Coord], k: int) -> tuple[Coord, ...] | None:
    """Striped pick: k free chips taken at an even stride across the
    row-major order — the load-balancing scatterer some stock schedulers
    approximate (spread across racks), and the geometric worst case for a
    collective: maximum pairwise hop distance for the same chip count."""
    if len(free) < k:
        return None
    ordered = sorted(free, key=topo.index)
    # stride >= 1 (len >= k), so int(i * stride) is strictly increasing —
    # the k picks are distinct by construction.
    stride = len(ordered) / k
    return tuple(ordered[int(i * stride)] for i in range(k))


class NaiveAllocator:
    """Count-only bookkeeping twin of :class:`tputopo.topology.slices.Allocator`."""

    def __init__(self, topo: ChipTopology):
        self.topo = topo
        self._used: set[Coord] = set()

    @property
    def free(self) -> frozenset[Coord]:
        return frozenset(c for c in self.topo.chips if c not in self._used)

    def allocate(self, k: int) -> tuple[Coord, ...] | None:
        picked = naive_pick(self.topo, self.free, k)
        if picked is not None:
            self._used.update(picked)
        return picked

    def release(self, chips) -> None:
        for c in chips:
            self._used.discard(tuple(c))
