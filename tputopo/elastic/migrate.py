"""Migration destination planning: pick the landing box *before* the
eviction.

The pre-elastic requeue is fire-and-forget — a defrag or preemption
victim goes back to the queue and re-places whenever the tiered
admission loop next reaches it, possibly much later, possibly nowhere.
Migration inverts the order: the engine first checks a destination
exists for the gang's shape (this module), only then evicts with
preserved progress and pushes a ``_MIGRATE`` event that re-places the
gang immediately.  No destination → plain requeue, nothing risked.

The search reuses the mask-native candidate vocabulary the sort hot
loop and the defrag planner place with: per node, ``Allocator.find``
restricted to the node's chip mask answers "does a k-box fit on this
host", and a gang of ``r`` members needs ``r`` distinct feasible hosts
inside one domain.  It is a *necessary*-condition screen, not the full
host-grid gang search — the landing goes through the real placement
policy, and when the destination is taken by a racing placement between
plan and land the abort is classified, never silent.
"""

from __future__ import annotations

#: Classified reasons a planned migration failed to land, in the order
#: the engine checks them.  ``destination_lost`` — the planned capacity
#: was taken by a racing placement between evict and land;
#: ``place_failed`` — capacity still screens feasible but the real
#: placer declined (host-grid contiguity, transient fault);
#: ``superseded`` — the gang already landed through the normal tiered
#: loop before the migrate event fired; ``victim_gone`` — the gang
#: completed or was re-evicted (stale incarnation) in between.
MIGRATE_ABORT_REASONS = ("destination_lost", "place_failed",
                        "superseded", "victim_gone")


def plan_destination(replicas: int, k: int, domains) -> str | None:
    """Slice id of the first domain (sorted order) holding ``replicas``
    distinct hosts with a free k-chip box each, or None.

    ``domains`` is an iterable of ``(slice_id, allocator, node_masks)``
    tuples sorted by slice id — the engine passes its twin allocators,
    the extender its derived-state domains; both speak the same mask
    vocabulary."""
    if replicas < 1 or k < 1:
        return None
    for sid, alloc, node_masks in domains:
        free = alloc.free_mask
        if free.bit_count() < replicas * k:
            continue
        hosts = 0
        for node in sorted(node_masks):
            node_mask = node_masks[node]
            node_free = node_mask & free
            if node_free.bit_count() < k:
                continue
            if alloc.find(k, free_mask=node_free,
                          within_mask=node_mask) is not None:
                hosts += 1
                if hosts >= replicas:
                    return sid
    return None
