# lint-corpus-relpath: tputopo/corpus/switches_bad.py
"""KNOWN-BAD kill-switch-audit corpus: an unregistered switch, a dead
off-path, a never-read flag, and a switch-guarded counter defeating
presence gating."""


class Engine:
    # BAD: class-level feature flag with no SWITCH_REGISTRY entry and no
    # `# kill-switch:` directive
    ROGUE_FAST_PATH = True

    ORPHAN = True  # kill-switch: registered but wired to nothing  # BAD

    TURBO = True  # kill-switch: demo switch with a dead off-path

    def __init__(self):
        # the eager seed that defeats presence gating below
        self._counters = {"turbo_folds": 0}

    def run(self):
        # BAD: TURBO's only read — no else and nothing after, so the
        # off-path is dead and byte-identity is unfalsifiable
        if self.TURBO:
            # BAD: switch-guarded increment of an eagerly-seeded counter
            self.inc("turbo_folds")

    def inc(self, name):
        self._counters[name] = self._counters.get(name, 0) + 1
