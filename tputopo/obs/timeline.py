"""Bounded time-series recorder: the fleet's trajectory, not its endpoint.

Every headline metric in the sim report is an end-of-run aggregate, yet
the questions the standing evaluation keeps asking — when does the fleet
saturate, how deep does the queue get before the watermarks bite, what
did defrag/preemption do to fragmentation *over time* — are
time-resolved.  :class:`TimelineRecorder` makes the trajectory a
first-class artifact with two hard properties:

- **Byte-deterministic.**  Fed virtual-time samples (the sim engine
  calls it at every event boundary), its emitted block is a pure
  function of the sample stream: same (seed, config) → same bytes,
  sequential or ``--jobs N``.  Nothing here reads a clock; timestamps
  come from the caller.
- **Fixed memory, pinned output.**  Retained points never exceed
  :data:`POINT_BUDGET`.  When the sealed-point count reaches the
  budget, adjacent points merge pairwise and the bucket stride doubles
  (power-of-two adjacent-bucket compaction), so a 40k-event XL run and
  a 500-event run both emit ≤ the same pinned point count — and a run
  short enough to fit emits every sample exactly (stride 1, lossless).

Each emitted point is a bucket of ``stride`` consecutive samples,
summarized to preserve what downsampling usually destroys: gauges keep
the bucket **max** (utilization, fragmentation, queue depth, running
gangs — peaks survive), ``free_chips`` keeps the bucket **min** (troughs
survive), cumulative series (watermark skips) keep the bucket-final
value, and event marks (conflict requeues / executed preemptions /
executed defrag cycles) are per-bucket counts that sum under merges.

Saturation analytics are computed EXACTLY from the raw stream (O(1)
state per sample), never from the downsampled buckets: saturation onset
(first time utilization crosses the threshold), peak queue depth and
its timestamp, time spent at/above the threshold (step-function
integral, same convention as the report's time-weighted means), and the
queue drain time after the last arrival.

:class:`TimelineSampler` is the live-extender variant: a background
thread feeds the same recorder wall-clock samples from a caller-supplied
gauge function, serving ``GET /debug/timeline`` and the matching
Prometheus gauges.  Wall clock is telemetry there, exactly like span
wall-ms in :mod:`tputopo.obs.tracer` — the deterministic contract
applies to the sim's virtual-time feed only.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left

#: The pinned point budget: every emitted timeline, whatever the run
#: length, carries at most this many points.  One definition — the sim
#: block, the CLI contract, and the live extender recorder all read it.
POINT_BUDGET = 256

#: Utilization at/above this fraction counts as saturated (the onset /
#: time-above analytics below).
SATURATION_UTIL = 0.90

#: Event-mark kinds, in emission order: conflict = eviction/requeue
#: churn (node failures, defrag evictions, preemption victims, crash
#: recoveries — everything through the one requeue path), preempt =
#: executed preemption plans, defrag = executed defrag cycles.
MARK_KINDS = ("conflict", "preempt", "defrag")

#: Extra mark kinds the elastic subsystem feeds (tputopo.elastic):
#: migrate = migration verbs initiated, resize = shrink/grow steps.
#: Armed per recorder via ``extra_marks`` ONLY when the engine runs
#: ``--elastic`` — a default-constructed recorder emits exactly the
#: pre-elastic marks dict, so timeline-on/elastic-off bytes are pinned.
ELASTIC_MARK_KINDS = ("migrate", "resize")

# Bucket slot layout (plain lists: merged thousands of times per run,
# so no per-point object/dict overhead on the sampling hot path).
_T, _N, _UTIL, _FRAG, _FREE, _QUEUE, _RUN, _WM = range(8)
_MARK0 = 8          # then one slot per mark kind; the per-tier
                    # queue-depth dict (or None) follows the marks, so
                    # its slot index depends on the recorder's mark set.


def _r(x: float, nd: int = 6) -> float:
    """Stable rounding, same convention as the sim report's ``_r``: every
    float the block emits passes through here so byte-determinism never
    hinges on repr noise."""
    return round(float(x), nd)


def _merge(a: list, b: list, nmarks: int = len(MARK_KINDS)) -> list:
    """Fold two ADJACENT buckets (a precedes b) into one: max gauges,
    min free, b's cumulative tail, summed marks, per-tier max.
    ``nmarks`` is the owning recorder's mark-kind count (the tier dict
    sits right after the mark slots)."""
    out = [
        b[_T], a[_N] + b[_N],
        a[_UTIL] if a[_UTIL] > b[_UTIL] else b[_UTIL],
        a[_FRAG] if a[_FRAG] > b[_FRAG] else b[_FRAG],
        a[_FREE] if a[_FREE] < b[_FREE] else b[_FREE],
        a[_QUEUE] if a[_QUEUE] > b[_QUEUE] else b[_QUEUE],
        a[_RUN] if a[_RUN] > b[_RUN] else b[_RUN],
        b[_WM],
    ]
    for k in range(nmarks):
        out.append(a[_MARK0 + k] + b[_MARK0 + k])
    tiers_i = _MARK0 + nmarks
    ta, tb = a[tiers_i], b[tiers_i]
    if ta is None:
        out.append(tb)
    elif tb is None:
        out.append(ta)
    else:
        merged = dict(ta)
        for name, d in tb.items():
            if merged.get(name, -1) < d:
                merged[name] = d
        out.append(merged)
    return out


class TimelineRecorder:
    """Bounded deterministic recorder of fleet gauges over caller time.

    Feed :meth:`sample` monotonically non-decreasing timestamps; call
    :meth:`mark` / :meth:`note_arrival` between samples (they fold into
    the next sample's bucket).  :meth:`block` emits the report dict and
    never mutates recorder state, so it is safe to call repeatedly."""

    __slots__ = ("budget", "sat_util", "stride", "samples", "_points",
                 "_cur", "_cur_n", "_pending_marks", "_tiers_seen",
                 "_marks", "_tiers_i",
                 "_prev_t", "_prev_util", "_onset_t", "_peak_q",
                 "_peak_q_t", "_above_s", "_last_arrival_t", "_drain_t")

    def __init__(self, budget: int = POINT_BUDGET,
                 sat_util: float = SATURATION_UTIL,
                 extra_marks: tuple[str, ...] = ()) -> None:
        self.budget = max(2, int(budget))
        self.sat_util = float(sat_util)
        # Mark vocabulary: the standing kinds plus caller extras (the
        # engine arms ELASTIC_MARK_KINDS only under --elastic).  Default
        # construction emits exactly the pre-elastic marks dict.
        self._marks = MARK_KINDS + tuple(extra_marks)
        self._tiers_i = _MARK0 + len(self._marks)
        self.stride = 1          # samples per sealed bucket (power of two)
        self.samples = 0
        self._points: list[list] = []
        self._cur: list | None = None
        self._cur_n = 0
        self._pending_marks = [0] * len(self._marks)
        self._tiers_seen = False
        # Exact analytics state (raw stream, step-function convention:
        # a gauge holds its value until the next sample).
        self._prev_t: float | None = None
        self._prev_util = 0.0
        self._onset_t: float | None = None
        self._peak_q = 0
        self._peak_q_t: float | None = None
        self._above_s = 0.0
        self._last_arrival_t: float | None = None
        self._drain_t: float | None = None

    # ---- feeders -----------------------------------------------------------

    def note_arrival(self, t: float) -> None:
        """A job arrived at ``t``: the drain clock restarts (drain time
        measures from the LAST arrival to the first empty-queue sample
        after it)."""
        self._last_arrival_t = t
        self._drain_t = None

    def mark(self, kind: str) -> None:
        """Count one event of ``kind`` (an entry of this recorder's mark
        vocabulary — :data:`MARK_KINDS` plus any armed extras) against
        the next sample's bucket."""
        self._pending_marks[self._marks.index(kind)] += 1

    def sample(self, t: float, util: float, frag: float, free_chips: int,
               queue_depth: int, running: int, wm_skips: int = 0,
               tier_depths: dict[str, int] | None = None) -> None:
        """One gauge sample at caller time ``t`` (virtual in the sim)."""
        self.samples += 1
        # Exact analytics, before the bucket fold.
        if self._prev_t is not None and t > self._prev_t \
                and self._prev_util >= self.sat_util:
            self._above_s += t - self._prev_t
        self._prev_t = t
        self._prev_util = util
        if util >= self.sat_util and self._onset_t is None:
            self._onset_t = t
        if queue_depth > self._peak_q:
            self._peak_q = queue_depth
            self._peak_q_t = t
        if queue_depth == 0 and self._drain_t is None \
                and self._last_arrival_t is not None:
            self._drain_t = t
        # Bucket fold.
        cur = self._cur
        if cur is None:
            cur = self._cur = [t, 1, util, frag, free_chips, queue_depth,
                               running, wm_skips, *self._pending_marks,
                               dict(tier_depths) if tier_depths else None]
        else:
            cur[_T] = t
            cur[_N] += 1
            if util > cur[_UTIL]:
                cur[_UTIL] = util
            if frag > cur[_FRAG]:
                cur[_FRAG] = frag
            if free_chips < cur[_FREE]:
                cur[_FREE] = free_chips
            if queue_depth > cur[_QUEUE]:
                cur[_QUEUE] = queue_depth
            if running > cur[_RUN]:
                cur[_RUN] = running
            cur[_WM] = wm_skips
            for k in range(len(self._marks)):
                cur[_MARK0 + k] += self._pending_marks[k]
            if tier_depths:
                ts = cur[self._tiers_i]
                if ts is None:
                    cur[self._tiers_i] = dict(tier_depths)
                else:
                    for name, d in tier_depths.items():
                        if ts.get(name, -1) < d:
                            ts[name] = d
        if tier_depths is not None:
            self._tiers_seen = True
        for k in range(len(self._marks)):
            self._pending_marks[k] = 0
        self._cur_n += 1
        if self._cur_n >= self.stride:
            self._points.append(cur)
            self._cur = None
            self._cur_n = 0
            if len(self._points) >= self.budget:
                self._compact()

    def _compact(self) -> None:
        """Merge adjacent point pairs in place: halves the point count,
        doubles the stride.  An odd trailing point carries over as-is
        (it simply represents fewer samples than its new stride)."""
        pts = self._points
        nm = len(self._marks)
        folded = [_merge(pts[i], pts[i + 1], nm)
                  for i in range(0, len(pts) - 1, 2)]
        if len(pts) % 2:
            folded.append(pts[-1])
        self._points = folded
        self.stride *= 2

    # ---- emission ----------------------------------------------------------

    def last_values(self) -> dict | None:
        """The most recent raw sample's gauges (the live /metrics
        surface), or None before the first sample."""
        cur = self._cur if self._cur is not None else (
            self._points[-1] if self._points else None)
        if cur is None:
            return None
        return {"t": cur[_T], "util": cur[_UTIL], "frag": cur[_FRAG],
                "free_chips": cur[_FREE], "queue_depth": cur[_QUEUE],
                "running": cur[_RUN]}

    def block(self) -> dict:
        """The report block: columnar point arrays + exact saturation
        analytics.  Pure read — never mutates recorder state — and
        every float passes the stable-rounding convention."""
        pts = list(self._points)
        if self._cur is not None:
            pts.append(self._cur)
        # The partial bucket can push the count to budget+0 at most
        # (compaction fires AT budget), but keep the pin explicit.
        while len(pts) > self.budget:
            folded = [_merge(pts[i], pts[i + 1], len(self._marks))
                      for i in range(0, len(pts) - 1, 2)]
            if len(pts) % 2:
                folded.append(pts[-1])
            pts = folded
        sat = {
            "onset_t": (_r(self._onset_t)
                        if self._onset_t is not None else None),
            "peak_queue_depth": self._peak_q,
            "peak_queue_t": (_r(self._peak_q_t)
                             if self._peak_q_t is not None else None),
            "above_util_s": _r(self._above_s),
            "util_threshold": _r(self.sat_util),
            "last_arrival_t": (_r(self._last_arrival_t)
                               if self._last_arrival_t is not None
                               else None),
            "drain_s": (_r(self._drain_t - self._last_arrival_t)
                        if self._drain_t is not None
                        and self._last_arrival_t is not None else None),
        }
        out = {
            "budget": self.budget,
            "points": len(pts),
            "samples": self.samples,
            "stride": self.stride,
            "t": [_r(p[_T]) for p in pts],
            "util": [_r(p[_UTIL]) for p in pts],
            "frag": [_r(p[_FRAG]) for p in pts],
            "free_chips": [p[_FREE] for p in pts],
            "queue_depth": [p[_QUEUE] for p in pts],
            "running": [p[_RUN] for p in pts],
            "wm_skips": [p[_WM] for p in pts],
            "marks": {kind: [p[_MARK0 + k] for p in pts]
                      for k, kind in enumerate(self._marks)},
            "saturation": sat,
        }
        if self._tiers_seen:
            # Per-tier pending depth, present only when the feed carried
            # tiers (the mixed workload) — same presence rule as the
            # report's tiers block.  Missing tier-in-bucket = depth 0.
            ti = self._tiers_i
            names = sorted({name for p in pts if p[ti]
                            for name in p[ti]})
            out["tiers"] = {name: [(p[ti] or {}).get(name, 0)
                                   for p in pts] for name in names}
        return out


def bucket_at(block: dict, t: float) -> dict | None:
    """The timeline bucket covering time ``t`` in an emitted ``block``
    (buckets are keyed by their END time, so this is the first bucket
    whose end >= t; the last bucket covers everything after).  Powers
    the A/B first-divergence annotation: WHAT the fleet looked like at
    the moment two policies' decision streams split."""
    ts = block.get("t") or []
    if not ts:
        return None
    i = min(bisect_left(ts, t), len(ts) - 1)
    return {
        "index": i,
        "t": ts[i],
        "util": block["util"][i],
        "frag": block["frag"][i],
        "free_chips": block["free_chips"][i],
        "queue_depth": block["queue_depth"][i],
        "running": block["running"][i],
    }


class TimelineSampler:
    """The live-extender feed: a background thread samples a caller
    gauge function every ``period_s`` wall seconds into an internal
    :class:`TimelineRecorder`, serving ``GET /debug/timeline``.

    ``sample_fn`` returns the recorder's gauge kwargs (``util``,
    ``frag``, ``free_chips``, ``queue_depth``, ``running``; optionals
    default).  ``clock`` stamps sample times (wall by default — live
    timelines are telemetry, like span wall-ms; tests inject a fake).
    ``metrics`` (an extender ``Metrics``) counts samples taken.  All
    recorder access goes through one lock: the sampler thread writes
    while HTTP handler threads read."""

    def __init__(self, sample_fn, *, period_s: float = 10.0,
                 budget: int = POINT_BUDGET, clock=time.time,
                 metrics=None) -> None:
        self.sample_fn = sample_fn
        self.period_s = max(0.1, float(period_s))
        self.clock = clock
        self.metrics = metrics
        self.recorder = TimelineRecorder(budget=budget)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last: dict | None = None  # most recent gauges (for /metrics)
        self.errors = 0

    def sample_once(self) -> None:
        """Take one sample now (the thread loop's body; tests call it
        directly).  Gauge-function failures count, never propagate — a
        flaky API read must not kill the sampler."""
        try:
            gauges = self.sample_fn()
        except Exception:
            # A failed gauge read is counted and skipped — the sampler
            # thread must survive any API blip.
            with self._lock:
                self.errors += 1
            return
        t = self.clock()
        with self._lock:
            self.recorder.sample(t, **gauges)
            self.last = {"t": t, **gauges}
        if self.metrics is not None:
            self.metrics.inc("timeline_samples")

    def block(self) -> dict:
        with self._lock:
            return self.recorder.block()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            self.sample_once()

    def start(self) -> "TimelineSampler":
        self._thread = threading.Thread(target=self._loop,
                                        name="tputopo-timeline",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
