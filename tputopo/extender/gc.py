"""Stale-assumption garbage collector.

The reference's two-phase handshake (bind stamps ASSUME_TIME + ASSIGNED=false;
Allocate confirms, design.md:223-246) leaves one failure mode open: a pod
bound but never started (node died, image pull stuck).  SURVEY.md §5.2-5.3
prescribes a GC that releases devices whose assumption is older than a TTL
and never confirmed.  :class:`ClusterState` already *ignores* expired
assumptions when computing occupancy; this sweeper makes the release
durable and observable by clearing the scheduling annotations on the pod —
generalized to the job level (the all-or-nothing token, SURVEY.md §7 "gang
scheduling semantics"): when any member of a gang expires, every *still
unconfirmed* member is released with it.  Confirmed members have running
containers; reclaiming their chips is a job-controller decision (delete the
pods), not a scheduler-side annotation wipe — the sweeper surfaces such
gangs in :attr:`stranded_gangs` instead of double-booking their chips.
"""

from __future__ import annotations

import time

from tputopo.k8s import objects as ko
from tputopo.k8s.fakeapi import Conflict, NotFound
from tputopo.k8s.retry import ApiUnavailable
from tputopo.extender.state import _pod_assignment_of, list_pods_nocopy


class AssumptionGC:
    # ``api_server`` is deliberately untyped: the sweeper runs against
    # every reader/writer shape the control plane uses — FakeApiServer,
    # the REST KubeApiClient, the sim's copy-free facade, the chaos
    # proxy — needing only list/patch_annotations.
    def __init__(self, api_server, assume_ttl_s: float = 60.0,
                 clock=time.time, metrics=None,
                 wall=time.perf_counter) -> None:
        self.api = api_server
        self.assume_ttl_s = assume_ttl_s
        self.clock = clock
        # Sweep-latency telemetry rides an injectable wall hook (the
        # clock=time.time default-arg idiom): it feeds the "gc" latency
        # series only — never expiry judgement, which is the injected
        # clock's — so the sim's use of the GC stays wall-clock-free
        # (clock-flow lint rule).
        self._wall = wall
        # Optional extender Metrics: sweeps were invisible to /metrics
        # scrapers (a wedged or slow GC could strand reservations silently)
        # — when wired, each pass records gc_sweeps/gc_assumptions_released
        # counters and a "gc" latency series, exported like every verb.
        self.metrics = metrics
        self.released: list[str] = []  # pod names released, for observability
        # Gangs with confirmed members whose unconfirmed members expired —
        # they hold chips but can never complete; a job controller must act.
        self.stranded_gangs: list[str] = []

    def sweep(self) -> list[str]:
        """One pass: clear assignments for expired assumptions (and their
        whole gangs).  Returns the pod names released this pass.

        The scan is direct: pods are filtered through the same
        :func:`_pod_assignment_of` parse sync() uses and judged against
        the TTL at one clock read — no :class:`ClusterState` build (the
        full sync here was ~20% of fleet-scale sim wall once the baseline
        policies stopped re-syncing; the sweep never needed allocators or
        topology, only the assignment annotations).  Victim ORDER is the
        old sync-derived order — expired assumptions in (assume_time,
        namespace, name) order, then gang-expanded members grouped by
        domain in node-list order — so release patch streams (and the
        fault draws a chaos run assigns to them) are byte-stable across
        the rewrite."""
        t0 = self._wall()
        now = self.clock()
        # TPU nodes only (the known-node gate sync applies), with each
        # slice's rank in node-name order — the domain iteration order the
        # gang expansion must reproduce.
        node_slice: dict[str, str] = {}
        slice_rank: dict[str, int] = {}
        try:
            nodes = self.api.list("nodes", copy=False)
        except TypeError:  # reader without a copy kwarg (fake/REST client)
            nodes = self.api.list("nodes")
        for node in nodes:
            anns = node["metadata"].get("annotations", {})
            sid = anns.get(ko.ANN_SLICE_ID)
            if sid is None or ko.ANN_TOPOLOGY not in anns:
                continue
            node_slice[node["metadata"]["name"]] = sid
            slice_rank.setdefault(sid, len(slice_rank))
        cands = []
        # tpulint: disable=hot-path-scan -- amortized: one O(pods) annotation scan per TTL-period sweep (gc_period = assume_ttl/2), the documented cost of durable assumption reclaim
        for pod in list_pods_nocopy(self.api):
            pa = _pod_assignment_of(pod)
            if pa is not None and pa.node_name in node_slice:
                cands.append(pa)
        cands.sort(key=lambda pa: (pa.assume_time, pa.namespace,
                                   pa.pod_name))
        victims: dict[tuple[str, str], None] = {}
        gangs: set[tuple[str, str]] = set()  # (namespace, gang_id)
        live: list = []
        for pa in cands:
            if not pa.assigned and now - pa.assume_time > self.assume_ttl_s:
                victims[(pa.namespace, pa.pod_name)] = None
                if pa.gang_id:
                    gangs.add((pa.namespace, pa.gang_id))
            else:
                live.append(pa)
        # Gang expansion: release every still-unconfirmed member of an
        # expired gang together (a partial gang holds chips a complete gang
        # needs); confirmed members are running — flag, don't release.
        stranded: set[str] = set()
        if gangs:
            members = [pa for pa in live
                       if pa.gang_id and (pa.namespace, pa.gang_id) in gangs]
            # Stable sort on the domain rank alone: domain-major, within a
            # domain the (assume_time, namespace, name) candidate order —
            # exactly the old per-domain assignment walk.
            members.sort(key=lambda pa: slice_rank[node_slice[pa.node_name]])
            for pa in members:
                if pa.assigned:
                    stranded.add(f"{pa.namespace}/{pa.gang_id}")
                else:
                    victims[(pa.namespace, pa.pod_name)] = None
        self.stranded_gangs.extend(sorted(stranded))
        del self.stranded_gangs[:-100]
        released = []
        for ns, name in victims:
            try:
                self.api.patch_annotations(
                    "pods", name,
                    {ko.ANN_GROUP: None, ko.ANN_ASSUME_TIME: None,
                     ko.ANN_ASSIGNED: None, ko.ANN_PREDICTED_GBPS: None},
                    namespace=ns,
                )
                released.append(f"{ns}/{name}")
            except NotFound:
                continue  # pod deleted meanwhile — already released
            except (ApiUnavailable, Conflict):
                # Transient API failure or a racing writer on ONE victim
                # must not abort the whole sweep (the other victims still
                # need releasing) and must not kill the GC loop: skip it —
                # the pod stays expired, so the next sweep retries.
                if self.metrics is not None:
                    self.metrics.inc("gc_release_errors")
                continue
        self.released.extend(released)
        del self.released[:-500]
        if self.metrics is not None:
            self.metrics.inc("gc_sweeps")
            self.metrics.inc("gc_assumptions_released", len(released))
            self.metrics.observe_ms("gc", (self._wall() - t0) * 1e3)
        return released
