"""Whole-program call graph over the repository's ASTs.

PR 7's checkers are per-function: every contract that crosses a call
boundary — a helper reading the wall clock on behalf of a ``clock``-taking
caller, a nocopy view laundered through a return value, two locks taken in
opposite orders on different paths — was invisible to them.  This module
builds the shared interprocedural substrate the graph-backed checkers
(:mod:`lockorder`, :mod:`clockflow`, :mod:`nocopyflow`, :mod:`excepts`,
:mod:`counters`) rebase on:

- **Definitions**: module-level functions, class methods (nested classes
  included), and nested functions, each a :class:`FunctionInfo` keyed by
  ``(relpath, qualname)``.
- **Import resolution**: ``from tputopo.x.y import A as B`` and
  ``import tputopo.x.y as m`` aliases resolve to the defining module's
  own definitions (re-export chains followed, cycle-safe).
- **Method resolution**: ``self.m()`` / ``cls.m()`` resolve through the
  class hierarchy (bases resolved across modules, C3-ish linearization);
  ``super().m()`` searches the bases only; ``Class.m()`` and
  ``Class()`` (constructor -> ``__init__``) resolve by name.
- **Attribute-type inference**: ``self.x = <param annotated T>`` /
  ``self.x = T(...)`` in a method body gives ``self.x.m()`` a resolution
  target when every assignment agrees on one repo class — how the
  scheduler's calls into ``self.api`` (a :class:`FakeApiServer`) become
  real edges.
- **Decorator passthrough**: a decorated ``def`` is still itself; calls
  to the name reach the underlying function whatever the wrapper.

Everything else — dynamic attributes, callables in containers, results
of calls — is a **conservatively unresolved** edge: :meth:`CallGraph.
resolve` returns ``None``, the call site is still listed (checkers can
apply name heuristics), and no checker may crash or silently widen a
guarantee because of one.

The graph is built once per lint run and shared: every graph-backed
checker funnels through :func:`graph_for`, which memoizes on the
identity of the module list (one entry — runs don't interleave).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable

from tputopo.lint.core import Module, dotted_name

__all__ = ["FunctionInfo", "ClassInfo", "CallSite", "CallGraph",
           "graph_for", "subclass_overrides"]


@dataclass
class FunctionInfo:
    """One function or method definition."""

    relpath: str
    qualname: str                       # "f", "Cls.m", "f.<locals>.g"
    node: ast.AST = field(repr=False)
    cls: "ClassInfo | None" = None      # enclosing class for methods
    parent: "FunctionInfo | None" = None  # enclosing function (nested defs)
    takes_clock: bool = False
    _locals: dict = field(default_factory=dict, repr=False)  # nested defs

    @property
    def key(self) -> tuple[str, str]:
        return (self.relpath, self.qualname)

    @property
    def display(self) -> str:
        return f"{self.relpath}::{self.qualname}"

    def param_names(self) -> list[str]:
        a = self.node.args
        return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


@dataclass
class ClassInfo:
    """One class definition (nested classes carry dotted qualnames)."""

    relpath: str
    qualname: str
    node: ast.AST = field(repr=False)
    base_exprs: list = field(default_factory=list, repr=False)
    bases: list["ClassInfo"] = field(default_factory=list, repr=False)
    methods: dict[str, FunctionInfo] = field(default_factory=dict,
                                             repr=False)
    #: self.<attr> -> ClassInfo inferred from assignments; the
    #: ``_CONFLICT`` sentinel blocks resolution when assignments disagree.
    attr_types: dict[str, "ClassInfo | None"] = field(default_factory=dict,
                                                      repr=False)

    @property
    def key(self) -> tuple[str, str]:
        return (self.relpath, self.qualname)

    @property
    def display(self) -> str:
        return f"{self.qualname}"

    def mro(self) -> list["ClassInfo"]:
        """Depth-first linearization, self first, duplicates dropped —
        close enough to C3 for method lookup in this codebase."""
        out, seen = [], set()
        stack = [self]
        while stack:
            c = stack.pop(0)
            if c.key in seen:
                continue
            seen.add(c.key)
            out.append(c)
            stack = list(c.bases) + stack
        return out

    def find_method(self, name: str) -> FunctionInfo | None:
        for c in self.mro():
            m = c.methods.get(name)
            if m is not None:
                return m
        return None


_CONFLICT = object()  # attr_types sentinel: assignments disagree


@dataclass
class CallSite:
    """One call expression inside a function, resolved when possible."""

    node: ast.Call = field(repr=False)
    caller: FunctionInfo
    callee: FunctionInfo | None         # None = conservatively unresolved
    dotted: str | None                  # static name text, for heuristics


def _module_dotted(relpath: str) -> str:
    """``tputopo/sim/engine.py`` -> ``tputopo.sim.engine``;
    ``tputopo/k8s/__init__.py`` -> ``tputopo.k8s``."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _ModuleScope:
    """Per-module namespace: imports, top-level defs, classes."""

    def __init__(self, mod: Module) -> None:
        self.mod = mod
        self.module_aliases: dict[str, str] = {}   # name -> dotted module
        self.object_aliases: dict[str, tuple[str, str]] = {}  # name ->
        #   (dotted module, original name)
        self.functions: dict[str, FunctionInfo] = {}  # top-level name
        self.classes: dict[str, ClassInfo] = {}       # top-level + nested

    def collect_imports(self) -> None:
        # Walk the whole tree: imports inside functions or TYPE_CHECKING
        # blocks still name real modules and still resolve.
        for node in self.mod.nodes():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.module_aliases[a.asname] = a.name
                    else:
                        # ``import a.b.c`` binds ``a``; dotted call text
                        # is matched by longest-module-prefix later.
                        root = a.name.split(".", 1)[0]
                        self.module_aliases.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                    and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.object_aliases[a.asname or a.name] = (node.module,
                                                               a.name)


class CallGraph:
    """The whole-program view.  Build with :meth:`build`, query with
    :meth:`resolve` / :meth:`callees` / :meth:`functions_under`."""

    def __init__(self) -> None:
        self.functions: dict[tuple[str, str], FunctionInfo] = {}
        self.classes: dict[tuple[str, str], ClassInfo] = {}
        self.scopes: dict[str, _ModuleScope] = {}        # by relpath
        self.by_dotted: dict[str, str] = {}              # dotted -> relpath
        self._callsites: dict[tuple[str, str], list[CallSite]] = {}
        self._callers: dict[tuple[str, str], list[CallSite]] | None = None
        self._resolve_memo: dict[tuple, FunctionInfo | None] = {}

    # ---- construction ------------------------------------------------------

    @classmethod
    def build(cls, modules: Iterable[Module]) -> "CallGraph":
        g = cls()
        mods = [m for m in modules if m.parse_error is None]
        for m in mods:
            g.by_dotted[_module_dotted(m.relpath)] = m.relpath
        for m in mods:
            scope = _ModuleScope(m)
            scope.collect_imports()
            g.scopes[m.relpath] = scope
            g._collect_defs(scope, m.tree.body, cls_info=None, parent=None)
        g._resolve_bases()
        g._infer_attr_types()
        return g

    def _collect_defs(self, scope: _ModuleScope, body, cls_info, parent,
                      prefix: str = "") -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + node.name
                fn = FunctionInfo(
                    relpath=scope.mod.relpath, qualname=qual, node=node,
                    cls=cls_info, parent=parent,
                    takes_clock="clock" in [
                        p.arg for p in (*node.args.posonlyargs,
                                        *node.args.args,
                                        *node.args.kwonlyargs)])
                self.functions[fn.key] = fn
                if cls_info is not None and parent is None:
                    cls_info.methods[node.name] = fn
                elif parent is not None:
                    parent._locals[node.name] = fn
                else:
                    scope.functions[node.name] = fn
                # Nested defs: their own FunctionInfos, parent-linked.
                self._collect_defs(scope, node.body, cls_info=cls_info,
                                   parent=fn,
                                   prefix=qual + ".<locals>.")
            elif isinstance(node, ast.ClassDef):
                qual = prefix + node.name
                ci = ClassInfo(relpath=scope.mod.relpath, qualname=qual,
                               node=node, base_exprs=list(node.bases))
                self.classes[ci.key] = ci
                # Top-level AND nested classes land in the module scope by
                # their dotted qualname; plain name for top-level.
                scope.classes[qual] = ci
                if prefix == "":
                    scope.classes[node.name] = ci
                self._collect_defs(scope, node.body, cls_info=ci,
                                   parent=parent, prefix=qual + ".")
            elif isinstance(node, (ast.If, ast.Try)):
                # Defs under guards (TYPE_CHECKING, version forks) still
                # exist; collect through one structural level.
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef)):
                        self._collect_defs(scope, [sub], cls_info, parent,
                                           prefix)

    def _resolve_bases(self) -> None:
        for ci in self.classes.values():
            scope = self.scopes[ci.relpath]
            for b in ci.base_exprs:
                target = self._resolve_class_expr(b, scope)
                if target is not None:
                    ci.bases.append(target)

    # ---- name/object resolution --------------------------------------------

    def _exported(self, relpath: str, name: str,
                  _seen: frozenset = frozenset()):
        """A (FunctionInfo | ClassInfo) named ``name`` in module
        ``relpath``, following re-export chains (``from x import name``)
        cycle-safely."""
        if (relpath, name) in _seen:
            return None
        scope = self.scopes.get(relpath)
        if scope is None:
            return None
        got = scope.functions.get(name) or scope.classes.get(name)
        if got is not None:
            return got
        chain = scope.object_aliases.get(name)
        if chain is not None:
            src_rel = self.by_dotted.get(chain[0])
            if src_rel is not None:
                return self._exported(src_rel, chain[1],
                                      _seen | {(relpath, name)})
        return None

    def _resolve_class_expr(self, expr: ast.AST,
                            scope: _ModuleScope) -> ClassInfo | None:
        """A class reference in an expression (base list, annotation)."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            # String annotation: parse and retry.
            try:
                expr = ast.parse(expr.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.BitOr):
            # ``T | None`` — the one non-None side that resolves wins.
            got = [self._resolve_class_expr(s, scope)
                   for s in (expr.left, expr.right)]
            got = [g for g in got if g is not None]
            return got[0] if len(got) == 1 else None
        if isinstance(expr, ast.Subscript):  # Optional[T] / list[T] -> T?
            if (d := dotted_name(expr.value)) and \
                    d.rsplit(".", 1)[-1] == "Optional":
                return self._resolve_class_expr(expr.slice, scope)
            return None
        dotted = dotted_name(expr)
        if dotted is None:
            return None
        return self._resolve_dotted_object(dotted, scope, want_class=True)

    def _resolve_dotted_object(self, dotted: str, scope: _ModuleScope,
                               want_class: bool = False):
        """``name`` / ``alias.attr`` / ``mod.Class.method`` ->
        FunctionInfo | ClassInfo | None."""
        parts = dotted.split(".")
        head = parts[0]
        # Local/imported object by bare name.
        if len(parts) == 1:
            got = self._exported(scope.mod.relpath, head)
            if got is None:
                return None
            if want_class:
                return got if isinstance(got, ClassInfo) else None
            return got
        # Module alias prefix (``ko.make_pod``, ``m.Class.method``) —
        # longest dotted-module match wins.
        if head in scope.module_aliases:
            base = scope.module_aliases[head]
            full = ".".join([base] + parts[1:])
            for cut in range(len(full.split(".")), 0, -1):
                mod_dotted = ".".join(full.split(".")[:cut])
                rel = self.by_dotted.get(mod_dotted)
                if rel is None:
                    continue
                rest = full.split(".")[cut:]
                return self._member_of_module(rel, rest, want_class)
        # ``Class.method`` / ``Class.Inner`` via a local or imported class.
        got = self._exported(scope.mod.relpath, head)
        if isinstance(got, ClassInfo):
            return self._member_of_class(got, parts[1:], want_class)
        return None

    def _member_of_module(self, relpath: str, rest: list[str],
                          want_class: bool):
        if not rest:
            return None
        got = self._exported(relpath, rest[0])
        if len(rest) == 1:
            if want_class:
                return got if isinstance(got, ClassInfo) else None
            return got
        if isinstance(got, ClassInfo):
            return self._member_of_class(got, rest[1:], want_class)
        return None

    def _member_of_class(self, ci: ClassInfo, rest: list[str],
                         want_class: bool):
        if len(rest) != 1:
            return None
        if want_class:
            inner = self.classes.get((ci.relpath,
                                      f"{ci.qualname}.{rest[0]}"))
            return inner
        return ci.find_method(rest[0])

    # ---- attribute-type inference ------------------------------------------

    def _infer_attr_types(self) -> None:
        for ci in self.classes.values():
            scope = self.scopes[ci.relpath]
            # Class-body annotations (``scheduler: ExtenderScheduler``
            # on a handler class) declare instance attributes as surely
            # as an __init__ assignment — the HTTP handler's calls into
            # the scheduler resolve through exactly this.
            for node in ci.node.body:
                if isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    got = self._resolve_class_expr(node.annotation, scope)
                    if got is not None:
                        ci.attr_types.setdefault(node.target.id, got)
            for meth in ci.methods.values():
                ann_of = self._param_annotations(meth, scope)
                for node in ast.walk(meth.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            continue
                        inferred = self._value_class(node.value, scope,
                                                     ann_of, cls=ci)
                        prev = ci.attr_types.get(t.attr)
                        if inferred is None:
                            # An un-inferable assignment poisons the attr:
                            # resolving through it could be wrong.
                            ci.attr_types[t.attr] = _CONFLICT
                        elif prev is None:
                            ci.attr_types[t.attr] = inferred
                        elif prev is not inferred:
                            ci.attr_types[t.attr] = _CONFLICT

    def _param_annotations(self, fn: FunctionInfo,
                           scope: _ModuleScope) -> dict[str, ClassInfo]:
        out = {}
        a = fn.node.args
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            if p.annotation is not None:
                got = self._resolve_class_expr(p.annotation, scope)
                if got is not None:
                    out[p.arg] = got
        return out

    def _value_class(self, expr: ast.AST, scope: _ModuleScope,
                     ann_of: dict[str, ClassInfo],
                     cls: ClassInfo | None = None) -> ClassInfo | None:
        """The repo class an assigned value is an instance of, if a
        single candidate is certain: a constructor call, an annotated
        parameter, or a call to a function whose return annotation
        resolves (``self.sched = self._make_scheduler()``)."""
        if isinstance(expr, ast.Name):
            return ann_of.get(expr.id)
        if isinstance(expr, ast.Call):
            d = dotted_name(expr.func)
            if d is None:
                return None
            got = self._resolve_dotted_object(d, scope, want_class=True)
            if got is not None:
                return got
            # Factory call: resolve the callee and use its return
            # annotation.  ``self.m()`` resolves through the class.
            parts = d.split(".")
            callee = None
            if parts[0] in ("self", "cls") and cls is not None \
                    and len(parts) == 2:
                callee = cls.find_method(parts[1])
            else:
                obj = self._resolve_dotted_object(d, scope)
                if isinstance(obj, FunctionInfo):
                    callee = obj
            if callee is not None and \
                    getattr(callee.node, "returns", None) is not None:
                return self._resolve_class_expr(
                    callee.node.returns, self.scopes[callee.relpath])
            return None
        if isinstance(expr, ast.IfExp):
            cands = {c.key: c for c in
                     (self._value_class(s, scope, ann_of, cls=cls)
                      for s in (expr.body, expr.orelse)) if c is not None}
            return next(iter(cands.values())) if len(cands) == 1 else None
        if isinstance(expr, ast.BoolOp):
            cands = {c.key: c for c in
                     (self._value_class(s, scope, ann_of, cls=cls)
                      for s in expr.values) if c is not None}
            return next(iter(cands.values())) if len(cands) == 1 else None
        return None

    def attr_type(self, ci: ClassInfo, attr: str) -> ClassInfo | None:
        got = None
        for c in ci.mro():
            got = c.attr_types.get(attr)
            if got is not None:
                break
        return None if got is _CONFLICT else got

    # ---- call resolution ---------------------------------------------------

    def resolve(self, call: ast.Call,
                fn: FunctionInfo) -> FunctionInfo | None:
        """The FunctionInfo a call lands in, or None (unresolved).
        Memoized per (function, call node) — several checkers resolve
        the same sites."""
        memo_key = (fn.key, id(call))
        if memo_key in self._resolve_memo:
            return self._resolve_memo[memo_key]
        got = self._resolve_target(call.func, fn)
        if isinstance(got, ClassInfo):            # constructor call
            got = got.find_method("__init__")
        if not isinstance(got, FunctionInfo):
            got = None
        self._resolve_memo[memo_key] = got
        return got

    def _resolve_target(self, func: ast.AST, fn: FunctionInfo):
        scope = self.scopes[fn.relpath]
        # super().m()
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Call)
                and isinstance(func.value.func, ast.Name)
                and func.value.func.id == "super" and fn.cls is not None):
            for base in fn.cls.mro()[1:]:
                m = base.methods.get(func.attr)
                if m is not None:
                    return m
            return None
        dotted = dotted_name(func)
        if dotted is None:
            return None
        parts = dotted.split(".")
        # Nested function visible in the enclosing def chain.
        if len(parts) == 1:
            p = fn
            while p is not None:
                local = p._locals.get(parts[0])
                if local is not None:
                    return local
                p = p.parent
        # self.m() / cls.m() / self.attr.m()
        if parts[0] in ("self", "cls") and fn.cls is not None:
            if len(parts) == 2:
                return fn.cls.find_method(parts[1])
            if len(parts) == 3:
                target_cls = self.attr_type(fn.cls, parts[1])
                if target_cls is not None:
                    return target_cls.find_method(parts[2])
            return None
        return self._resolve_dotted_object(dotted, scope)

    def callees(self, fn: FunctionInfo) -> list[CallSite]:
        """Every call expression in ``fn``'s own body (nested defs are
        their own functions), resolved where possible.  Cached."""
        got = self._callsites.get(fn.key)
        if got is not None:
            return got
        sites: list[CallSite] = []
        stack = list(getattr(fn.node, "body", []))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate FunctionInfo / class scope
            if isinstance(node, ast.Call):
                sites.append(CallSite(node=node, caller=fn,
                                      callee=self.resolve(node, fn),
                                      dotted=dotted_name(node.func)))
            stack.extend(ast.iter_child_nodes(node))
        sites.sort(key=lambda s: (s.node.lineno, s.node.col_offset))
        self._callsites[fn.key] = sites
        return sites

    def callers_of(self, fn: FunctionInfo) -> list[CallSite]:
        if self._callers is None:
            self._callers = {}
            for f in list(self.functions.values()):
                for site in self.callees(f):
                    if site.callee is not None:
                        self._callers.setdefault(site.callee.key,
                                                 []).append(site)
        return self._callers.get(fn.key, [])

    def functions_under(self, *prefixes: str,
                        files: tuple[str, ...] = ()) -> list[FunctionInfo]:
        return [f for f in self.functions.values()
                if f.relpath.startswith(prefixes) or f.relpath in files]

    def closure_with_parents(self, roots, expand=None, skip_site=None
                             ) -> dict[tuple, tuple | None]:
        """Forward closure over resolved call edges from ``roots``:
        ``{function key: parent key (None for a root)}`` — the parent
        chain doubles as one example entry path for findings.
        ``expand(callee)`` may return extra FunctionInfos a call also
        reaches (virtual-dispatch widening); ``skip_site(caller, site)``
        — when given — prunes propagation through a call site the
        analysis has proven unreachable in its context (the ownership
        rule's sanctioned single-owner downgrade branches).  Shared by
        the lockset, hot-path-scan and ownership-flow root closures so
        path rendering and reachability can never drift between them."""
        parent: dict[tuple, tuple | None] = {k: None for k in roots}
        work = list(roots)
        while work:
            key = work.pop()
            fn = self.functions.get(key)
            if fn is None:
                continue
            targets = []
            for site in self.callees(fn):
                if site.callee is None:
                    continue
                if skip_site is not None and skip_site(fn, site):
                    continue
                targets.append(site.callee)
                if expand is not None:
                    targets.extend(expand(site.callee))
            for callee in targets:
                if callee.key not in parent:
                    parent[callee.key] = key
                    work.append(callee.key)
        return parent

    def render_entry_path(self, parent: dict, key: tuple,
                          hops: int = 6) -> str:
        """``root -> ... -> fn`` along the parent chain, elided past
        ``hops`` — the finding-message spelling shared by every
        closure-backed rule."""
        chain, k = [], key
        while k is not None and len(chain) < hops:
            fn = self.functions.get(k)
            chain.append(fn.qualname if fn is not None else str(k))
            k = parent.get(k)
        chain.reverse()
        return " -> ".join(chain)

    def fixpoint(self, seed: set[tuple[str, str]],
                 stop=None) -> set[tuple[str, str]]:
        """Backward closure: keys of functions that (transitively) call a
        seed function.  ``stop(fn)`` prunes propagation through a caller
        (the caller itself is still included — its own call is direct)."""
        out = set(seed)
        work = list(seed)
        while work:
            key = work.pop()
            fn = self.functions.get(key)
            if fn is None:
                continue
            for site in self.callers_of(fn):
                ck = site.caller.key
                if ck in out:
                    continue
                out.add(ck)
                if stop is None or not stop(site.caller):
                    work.append(ck)
        return out


def subclass_overrides(graph: CallGraph) -> dict[tuple, list]:
    """``method key -> overriding FunctionInfos in subclasses`` — the
    virtual-dispatch widening every closure-backed rule shares: a call
    resolving to a base-class method also reaches every subclass
    override (the sim's ``policy.place`` polymorphism is precisely how
    an expensive or forbidden path hides from a naive closure).
    Memoized on the graph so hot-path-scan and ownership-flow pay one
    build between them."""
    got = getattr(graph, "_overrides_memo", None)
    if got is not None:
        return got
    by_class: dict[tuple, list[ClassInfo]] = {}
    for ci in graph.classes.values():
        for b in ci.mro()[1:]:
            by_class.setdefault(b.key, []).append(ci)
    out: dict[tuple, list] = {}
    for ci_key, subs in by_class.items():
        base = graph.classes.get(ci_key)
        if base is None:
            continue
        for name, meth in base.methods.items():
            overrides = [s.methods[name] for s in subs
                         if name in s.methods]
            if overrides:
                out.setdefault(meth.key, []).extend(overrides)
    graph._overrides_memo = out
    return out


#: One-entry build cache: every graph-backed checker in a run sees the
#: same module list, so the first ``finalize`` builds and the rest reuse.
_CACHE: tuple[tuple[int, ...], CallGraph] | None = None


def graph_for(modules: list[Module]) -> CallGraph:
    global _CACHE
    key = tuple(id(m) for m in modules)
    if _CACHE is not None and _CACHE[0] == key:
        return _CACHE[1]
    graph = CallGraph.build(modules)
    _CACHE = (key, graph)
    return graph
