"""The ``lock-order`` checker: no deadlockable acquisition cycles.

The ``lock`` rule (locks.py) proves every guarded attribute is touched
under *a* lock; it says nothing about taking two locks in opposite
orders on different paths — the classic deadlock nobody reproduces in a
test.  This rule derives the **lock-acquisition graph** whole-program:

- Locks are discovered where they are born: ``self._x =
  threading.Lock()`` / ``RLock()`` in ``__init__``.  A ``threading.
  Condition(self._x)`` is an *alias* of ``_x`` (same underlying lock —
  the fake API's ``_watch_cond`` pattern), inferred, not annotated.
- Acquisitions are ``with self._x:`` blocks; ``# holds-lock: _x`` on a
  ``def`` line seeds the entry held-set (the caller-holds convention the
  ``lock`` rule already uses).
- Held-lock sets propagate through **call edges**: a call made while
  holding K reaches every lock the callee (transitively) acquires, so
  ``K -> L`` edges appear even when the two ``with`` blocks live in
  different classes and files.  Unresolved calls propagate nothing
  (conservative).

Findings:

- any cycle in the acquisition graph (potential deadlock), reported once
  per cycle with one example site per edge;
- re-acquisition of a non-reentrant ``Lock`` while already held (direct
  or through a call) — self-deadlock;
- any acquisition violating the declared canonical order: a module
  directive comment ``# lock-order: A._x > B._y > C._z`` (outermost
  first) pins the legal nesting; acquiring an earlier lock while holding
  a later one is a finding even before it closes into a cycle.
  Directives merge across modules; contradictions are findings.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tputopo.lint.callgraph import (CallGraph, ClassInfo, FunctionInfo,
                                    graph_for)
from tputopo.lint.core import Checker, Finding, Module

_ORDER_RE = re.compile(r"#\s*lock-order:\s*(?P<order>[\w.\s>]+)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(?P<locks>[\w|]+)")

#: (class-key, attr) -> display text
LockKey = tuple[tuple[str, str], str]


class _LockDecl:
    __slots__ = ("cls", "attr", "kind", "line")  # line: declaration site

    def __init__(self, cls: ClassInfo, attr: str, kind: str,
                 line: int) -> None:
        self.cls = cls
        self.attr = attr
        self.kind = kind  # "Lock" | "RLock" | "Condition"
        self.line = line

    @property
    def key(self) -> LockKey:
        return (self.cls.key, self.attr)

    @property
    def display(self) -> str:
        return f"{self.cls.qualname}.{self.attr}"

    @property
    def reentrant(self) -> bool:
        # A Condition aliases its (usually R)Lock; aliases canonicalize
        # to the base attr before this is consulted.
        return self.kind == "RLock"


def discover_locks(graph: CallGraph) -> tuple[
        dict[LockKey, _LockDecl], dict[tuple, dict[str, str]]]:
    """All declared locks, plus per-class alias maps
    (attr -> canonical attr, identity included).  Shared with the
    path-sensitive ``lockset`` rule — one lock vocabulary, one
    Condition-alias inference."""
    locks: dict[LockKey, _LockDecl] = {}
    aliases: dict[tuple, dict[str, str]] = {}
    for ci in graph.classes.values():
        init = ci.methods.get("__init__")
        if init is None:
            continue
        amap: dict[str, str] = {}
        for node in ast.walk(init.node):
            if not isinstance(node, ast.Assign):
                continue
            target = node.targets[0] if len(node.targets) == 1 else None
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            call = node.value
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id == "threading"):
                continue
            kind = call.func.attr
            if kind in ("Lock", "RLock"):
                decl = _LockDecl(ci, target.attr, kind, node.lineno)
                locks[decl.key] = decl
                amap[target.attr] = target.attr
            elif kind == "Condition":
                base = None
                if call.args and isinstance(call.args[0], ast.Attribute) \
                        and isinstance(call.args[0].value, ast.Name) \
                        and call.args[0].value.id == "self":
                    base = call.args[0].attr
                if base is not None and base in amap:
                    amap[target.attr] = amap[base]  # alias, same lock
                else:
                    decl = _LockDecl(ci, target.attr, "Condition",
                                     node.lineno)
                    locks[decl.key] = decl  # Condition owns its lock
                    amap[target.attr] = target.attr
        if amap:
            aliases[ci.key] = amap
    return locks, aliases


def canonical_lock(fn: FunctionInfo, attr: str,
                   locks: dict[LockKey, _LockDecl],
                   aliases: dict) -> _LockDecl | None:
    """The lock declaration ``self.<attr>`` refers to inside ``fn``,
    through the class alias maps (Condition -> base lock), or None."""
    if fn.cls is None:
        return None
    for c in fn.cls.mro():
        amap = aliases.get(c.key)
        if amap and attr in amap:
            return locks.get((c.key, amap[attr]))
    return None


def entry_held_locks(mod: Module, fn: FunctionInfo,
                     locks, aliases) -> frozenset[LockKey]:
    """The ``# holds-lock:`` entry set of ``fn``, canonicalized."""
    m = _HOLDS_RE.search(mod.comment_on_or_above(fn.node.lineno))
    if m is None:
        return frozenset()
    held = set()
    for attr in m.group("locks").split("|"):
        decl = canonical_lock(fn, attr, locks, aliases)
        if decl is not None:
            held.add(decl.key)
    return frozenset(held)


class LockOrderChecker(Checker):
    rule = "lock-order"
    description = ("lock acquisitions (with self.<lock>:, held sets "
                   "propagated through call edges) must be acyclic and "
                   "respect the declared `# lock-order:` canonical order")

    def __init__(self) -> None:
        self._mods: list[Module] = []

    def applies_to(self, relpath: str) -> bool:
        # Whole-program module set, shared with the other graph-backed
        # checkers (one cached build); findings are scoped to tputopo/.
        return relpath.startswith(("tputopo/", "tests/"))

    def check_module(self, mod: Module) -> Iterable[Finding]:
        self._mods.append(mod)
        return ()

    # ---- discovery (module-level helpers, shared with lockset) -------------

    def _discover_locks(self, graph: CallGraph):
        return discover_locks(graph)

    def _canonical(self, fn: FunctionInfo, attr: str,
                   locks: dict[LockKey, _LockDecl],
                   aliases: dict) -> _LockDecl | None:
        return canonical_lock(fn, attr, locks, aliases)

    def _entry_held(self, mod: Module, fn: FunctionInfo,
                    locks, aliases) -> frozenset[LockKey]:
        return entry_held_locks(mod, fn, locks, aliases)

    # ---- per-function scan -------------------------------------------------

    def _scan(self, fn: FunctionInfo, graph: CallGraph, locks, aliases,
              entry_held: frozenset[LockKey]):
        """(acquisitions, calls): each acquisition is (lock-key, held-
        before, node); each call is (callee, held, node)."""
        acqs: list[tuple[LockKey, frozenset, ast.AST]] = []
        calls: list[tuple[FunctionInfo, frozenset, ast.AST]] = []

        def visit(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return  # separate scope; held conservatively dropped
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    e = item.context_expr
                    if isinstance(e, ast.Attribute) \
                            and isinstance(e.value, ast.Name) \
                            and e.value.id == "self":
                        decl = self._canonical(fn, e.attr, locks, aliases)
                        if decl is not None:
                            acqs.append((decl.key, inner, e))
                            inner = inner | {decl.key}
                    visit(e, held)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                callee = graph.resolve(node, fn)
                if callee is not None:
                    calls.append((callee, held, node))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in getattr(fn.node, "body", []):
            visit(stmt, entry_held)
        return acqs, calls

    # ---- the analysis ------------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        mods, self._mods = self._mods, []
        graph = graph_for(mods)
        by_path = {m.relpath: m for m in mods}
        locks, aliases = self._discover_locks(graph)
        if not locks:
            return

        scans: dict[tuple, tuple] = {}
        for fn in graph.functions.values():
            if not fn.relpath.startswith("tputopo/"):
                continue  # test-local locks are not the contract
            mod = by_path.get(fn.relpath)
            if mod is None:
                continue
            if "with self." not in mod.source \
                    and "holds-lock" not in mod.source:
                # No acquisition can originate in this module (an
                # acquisition is literally ``with self.<lock>:``); the
                # function still forwards transitive acquisitions, so
                # its calls come from the shared cached walk, all with
                # an empty held set.
                scans[fn.key] = ([], [(s.callee, frozenset(), s.node)
                                      for s in graph.callees(fn)
                                      if s.callee is not None])
                continue
            entry = self._entry_held(mod, fn, locks, aliases)
            scans[fn.key] = self._scan(fn, graph, locks, aliases, entry)

        # Transitive acquisition sets per function (worklist fixpoint —
        # recursion-safe).
        all_acq: dict[tuple, frozenset[LockKey]] = {
            key: frozenset(a for a, _, _ in scan[0])
            for key, scan in scans.items()}
        changed = True
        while changed:
            changed = False
            for key, (_, calls) in scans.items():
                merged = all_acq[key]
                for callee, _, _ in calls:
                    merged = merged | all_acq.get(callee.key, frozenset())
                if merged != all_acq[key]:
                    all_acq[key] = merged
                    changed = True

        # Edges K -> L with one example site each; plus direct findings.
        edges: dict[LockKey, dict[LockKey, tuple[str, ast.AST]]] = {}
        findings: list[Finding] = []

        def add_edge(k: LockKey, l: LockKey, relpath: str,
                     node: ast.AST) -> None:
            edges.setdefault(k, {}).setdefault(l, (relpath, node))

        for key, (acqs, calls) in sorted(scans.items()):
            fn = graph.functions[key]
            for lock_key, held, node in acqs:
                if lock_key in held:
                    if not locks[lock_key].reentrant:
                        findings.append(Finding(
                            fn.relpath, node.lineno, node.col_offset,
                            self.rule,
                            f"re-acquisition of non-reentrant lock "
                            f"{locks[lock_key].display} while already "
                            "held — self-deadlock"))
                    continue
                for held_key in held:
                    add_edge(held_key, lock_key, fn.relpath, node)
            for callee, held, node in calls:
                if not held:
                    continue
                for lock_key in all_acq.get(callee.key, ()):
                    if lock_key in held:
                        if not locks[lock_key].reentrant:
                            findings.append(Finding(
                                fn.relpath, node.lineno, node.col_offset,
                                self.rule,
                                f"call into {callee.qualname}() while "
                                f"holding {locks[lock_key].display}, which "
                                "it re-acquires — self-deadlock on a "
                                "non-reentrant lock"))
                        continue
                    for held_key in held:
                        add_edge(held_key, lock_key, fn.relpath, node)

        findings.extend(self._cycle_findings(edges, locks))
        findings.extend(self._order_findings(mods, edges, locks))
        yield from findings

    def _cycle_findings(self, edges, locks) -> Iterable[Finding]:
        # Tarjan over the lock graph; any SCC with >1 lock is a
        # potential-deadlock cycle.
        index: dict[LockKey, int] = {}
        low: dict[LockKey, int] = {}
        on: set[LockKey] = set()
        stack: list[LockKey] = []
        sccs: list[list[LockKey]] = []
        counter = [0]
        nodes = sorted(set(edges) | {l for m in edges.values() for l in m})

        def strongconnect(v: LockKey) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in sorted(edges.get(v, {})):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(comp)

        for v in nodes:
            if v not in index:
                strongconnect(v)
        for comp in sccs:
            comp = sorted(comp)
            names = " <-> ".join(locks[k].display for k in comp)
            sites = []
            for k in comp:
                for l, (relpath, node) in sorted(edges.get(k, {}).items()):
                    if l in comp:
                        sites.append(f"{locks[k].display}->"
                                     f"{locks[l].display} at "
                                     f"{relpath}:{node.lineno}")
            relpath, node = next(iter(edges[comp[0]].values()))
            yield Finding(
                relpath, node.lineno, node.col_offset, self.rule,
                f"lock-acquisition cycle (potential deadlock): {names} "
                f"[{'; '.join(sites)}]")

    def _order_findings(self, mods, edges, locks) -> Iterable[Finding]:
        order, directive_findings = self._collect_order(mods, locks)
        yield from directive_findings
        if not order:
            return
        pos = {key: i for i, key in enumerate(order)}
        for k, targets in sorted(edges.items()):
            for l, (relpath, node) in sorted(targets.items()):
                if k in pos and l in pos and pos[l] < pos[k]:
                    yield Finding(
                        relpath, node.lineno, node.col_offset, self.rule,
                        f"acquires {locks[l].display} while holding "
                        f"{locks[k].display} — the declared lock-order "
                        f"puts {locks[l].display} first (outermost); "
                        "invert the nesting or fix the directive")

    def _collect_order(self, mods, locks) -> tuple[list[LockKey],
                                                   list[Finding]]:
        """Merge every module's ``# lock-order:`` directive into one
        order; contradictions and unknown lock names are findings."""
        by_display = {d.display: d.key for d in locks.values()}
        order: list[LockKey] = []
        findings: list[Finding] = []
        for mod in mods:
            if "lock-order" not in mod.source:
                continue  # directives only; skip the tokenize
            for line_no, text in sorted(mod.comments.items()):
                m = _ORDER_RE.search(text)
                if m is None:
                    continue
                names = [n.strip() for n in m.group("order").split(">")
                         if n.strip()]
                keys = []
                for name in names:
                    key = by_display.get(name)
                    if key is None:
                        findings.append(Finding(
                            mod.relpath, line_no, 0, self.rule,
                            f"lock-order directive names unknown lock "
                            f"{name!r} (known: "
                            f"{sorted(by_display)})"))
                    else:
                        keys.append(key)
                # Merge: the new sequence must be consistent with the
                # accumulated order on shared locks.
                shared = [k for k in keys if k in order]
                if shared != [k for k in order if k in keys]:
                    findings.append(Finding(
                        mod.relpath, line_no, 0, self.rule,
                        "lock-order directive contradicts an earlier "
                        "directive's relative order"))
                    continue
                merged: list[LockKey] = []
                oi = ki = 0
                while oi < len(order) or ki < len(keys):
                    if oi < len(order) and order[oi] not in keys:
                        merged.append(order[oi])
                        oi += 1
                    elif ki < len(keys) and keys[ki] not in order:
                        merged.append(keys[ki])
                        ki += 1
                    elif oi < len(order):
                        merged.append(order[oi])
                        oi += 1
                        ki += 1
                    else:
                        break
                order = merged
        return order, findings
