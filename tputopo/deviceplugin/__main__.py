"""Device-plugin CLI — the node agent entry point.

``python -m tputopo.deviceplugin`` probes the local host through the
discovery shim (native libtputopo.so when built, pure-Python twin
otherwise) and prints the node annotations + device list — the dry-run
half of the bring-up flow (SURVEY.md §3.1).  Use
``TPUTOPO_FAKE="v5p:2x2x4@0"`` on a box without TPUs.

``--serve`` runs the real node agent (design.md:57-86, 237-246):

1. publish topology annotations onto this Node via the API server
   (in-cluster service account, or ``--api-server`` for dev clusters);
2. bind the ``v1beta1.DevicePlugin`` gRPC service on a unix socket under
   the kubelet device-plugin dir and Register with the kubelet
   (grpc_transport.py; requires grpcio — in the tputopo[grpc] extra);
3. heartbeat: re-probe every ``--interval`` seconds; probe degradation
   flips every chip Unhealthy (streamed to the kubelet AND re-published
   as node annotations so the extender stops placing here — the
   health->scheduler loop); recovery flips them back; a topology change
   re-publishes annotations.

Without a kubelet socket (dev box) the agent still publishes annotations
and heartbeats — the scheduling plane is fully testable against it; only
the container-wiring leg needs the kubelet.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _make_api_server(args):
    """In-cluster service-account client, --api-server URL, or an
    in-process fake (pure dry-run)."""
    if args.api_server:
        from tputopo.k8s.client import KubeApiClient
        return KubeApiClient(base_url=args.api_server), args.api_server
    if os.environ.get("KUBERNETES_SERVICE_HOST"):
        from tputopo.k8s.client import KubeApiClient
        return KubeApiClient(), "in-cluster"
    from tputopo.k8s.fakeapi import FakeApiServer
    return FakeApiServer(), "fake (dry-run)"


def _make_kubelet(args, in_cluster: bool):
    """In-cluster the kubelet leg is mandatory: wait for kubelet.sock (node
    bootstrap / kubelet restart) and fail loudly on timeout so the
    DaemonSet restarts us — silently downgrading to the in-process fake
    while still publishing schedulable annotations would strand every pod
    the extender places here.  Dev boxes (fake API server) run
    annotations-only without a socket."""
    from tputopo.deviceplugin import grpc_transport as gt
    kubelet_sock = os.path.join(args.kubelet_dir, gt.KUBELET_SOCKET)
    deadline = time.monotonic() + args.kubelet_wait
    while not os.path.exists(kubelet_sock):
        if not in_cluster:
            from tputopo.deviceplugin.api import FakeKubelet
            return FakeKubelet(), "none (annotations-only dev mode)"
        if time.monotonic() >= deadline:
            raise RuntimeError(
                f"kubelet socket {kubelet_sock} did not appear within "
                f"{args.kubelet_wait}s")
        time.sleep(1.0)
    try:
        import grpc  # noqa: F401
    except ImportError:
        if in_cluster:
            raise RuntimeError(
                "kubelet socket present but grpcio missing; install the "
                "tputopo[grpc] extra in the node-agent image") from None
        from tputopo.deviceplugin.api import FakeKubelet
        return FakeKubelet(), "none (annotations-only dev mode)"
    return gt.GrpcKubelet(kubelet_dir=args.kubelet_dir), kubelet_sock


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="tputopo-device-plugin",
        description="TPU topology node agent (probe, annotate, serve kubelet)")
    ap.add_argument("--node-name",
                    default=os.environ.get("NODE_NAME", "local"))
    ap.add_argument("--slice-id",
                    default=os.environ.get("TPU_SLICE_ID", "slice-local"))
    ap.add_argument("--native", action="store_true",
                    help="require the native libtputopo.so probe (no fallback)")
    ap.add_argument("--serve", action="store_true",
                    help="run the node agent: annotate, serve the kubelet "
                         "device-plugin socket, heartbeat health")
    ap.add_argument("--interval", type=float, default=30.0)
    ap.add_argument("--kubelet-dir", default="/var/lib/kubelet/device-plugins")
    ap.add_argument("--kubelet-wait", type=float, default=300.0,
                    help="seconds to wait for kubelet.sock in-cluster")
    ap.add_argument("--api-server", default=None,
                    help="API server base URL (default: in-cluster config, "
                         "else an in-process fake)")
    ap.add_argument("--max-iterations", type=int, default=0,
                    help="stop the serve loop after N heartbeats (tests)")
    args = ap.parse_args()

    from tputopo.discovery import shim
    from tputopo.deviceplugin.reporter import node_annotations_for_probe

    if args.native:
        if shim.ensure_native_built() is None:
            print("error: native libtputopo.so unavailable and --native given",
                  file=sys.stderr)
            return 2
    probe = shim.probe_host()
    if not probe.ok:
        print(f"error: {probe.error}", file=sys.stderr)
        return 1
    out = {
        "backend": probe.backend,
        "node": args.node_name,
        "annotations": node_annotations_for_probe(probe, args.slice_id,
                                                  drop_none=True),
        "devices": [c for c in probe.chips],
    }
    print(json.dumps(out, indent=2))
    if not args.serve:
        return 0

    from tputopo.deviceplugin.plugin import TpuDevicePlugin, coord_id

    api_server, api_desc = _make_api_server(args)
    in_cluster = api_desc != "fake (dry-run)"
    kubelet, kubelet_desc = _make_kubelet(args, in_cluster)
    plugin = TpuDevicePlugin(
        node_name=args.node_name, slice_id=args.slice_id,
        kubelet=kubelet, api_server=api_server, probe=probe)

    degraded = False
    iterations = 0
    all_ids = [coord_id(c["coords"]) for c in probe.chips]
    from tputopo.deviceplugin import grpc_transport as gt
    own_sock = os.path.join(args.kubelet_dir, f"tputopo-{args.node_name}.sock")
    try:
        # Inside the try: a failed registration must still stop the gRPC
        # server's non-daemon threads, or the process hangs instead of
        # crash-looping visibly.
        plugin.start()
        print(json.dumps({"event": "serving", "api_server": api_desc,
                          "kubelet": str(kubelet_desc)}), flush=True)
        while args.max_iterations <= 0 or iterations < args.max_iterations:
            time.sleep(args.interval)
            iterations += 1
            if isinstance(kubelet, gt.GrpcKubelet) and not os.path.exists(own_sock):
                # Kubelet restarted and wiped the device-plugin dir: the
                # v1beta1 contract expects plugins to re-register.  Exit so
                # the DaemonSet restarts us into a fresh registration.
                print(json.dumps({"event": "kubelet-restarted"}),
                      file=sys.stderr, flush=True)
                return 4
            fresh = shim.probe_host()
            if not fresh.ok:
                if not degraded:
                    # Probe lost the chips: everything on this node is
                    # unschedulable until it recovers — one frame, one patch.
                    plugin.set_health_batch(all_ids, healthy=False)
                    degraded = True
                    print(json.dumps({"event": "probe-degraded",
                                      "error": fresh.error}), file=sys.stderr,
                          flush=True)
                continue
            if degraded:
                plugin.set_health_batch(all_ids, healthy=True)
                degraded = False
                print(json.dumps({"event": "probe-recovered"}), flush=True)
            if fresh.chips != probe.chips:
                # Topology changed under us (re-cabling, chip swap):
                # restart the agent cleanly rather than serve a stale
                # device list — the DaemonSet restarts the pod and
                # re-registration follows.
                print(json.dumps({"event": "topology-changed",
                                  "devices": list(fresh.chips)}), flush=True)
                return 3
        return 0
    finally:
        # The gRPC server holds non-daemon threads; without this the
        # process never exits after the loop ends or a signal lands.
        stop = getattr(kubelet, "stop", None)
        if stop is not None:
            stop()


if __name__ == "__main__":
    raise SystemExit(main())
