"""In-memory Kubernetes API server double.

Stands in for the API server + etcd (reference component 2.16: Gaia persists
assignments in etcd, PDF §III.C step 5; the design keeps them in pod
annotations, design.md:223-234).  Implements just what the framework's
control flows use: typed object store, strategic-merge-style metadata
patches with optimistic concurrency (resourceVersion), pod binding, and a
simple event list for test assertions.

Thread-safe: the extender HTTP server and device-plugin confirm leg hit it
concurrently (the bind-vs-allocate race the handshake exists for,
SURVEY.md §3.3 note).
"""

from __future__ import annotations

import copy
import hashlib
import json
import threading
import time
from bisect import bisect_left, insort
from typing import Callable, Iterable

from tputopo.k8s.objects import ANN_GROUP


class NotFound(KeyError):
    pass


class Conflict(RuntimeError):
    """resourceVersion mismatch — the optimistic-concurrency signal."""


class Gone(RuntimeError):
    """Watch resourceVersion fell off the retained event window (HTTP 410)
    — the informer must relist."""


def _key(namespace: str | None, name: str) -> tuple[str, str]:
    return (namespace or "", name)


def parse_label_selector(sel: str) -> dict[str, str]:
    """``"a=b,c=d"`` -> {"a": "b", "c": "d"} (equality terms only — all the
    framework uses)."""
    out = {}
    for term in sel.split(","):
        term = term.strip()
        if not term:
            continue
        k, _, v = term.partition("=")
        out[k.strip()] = v.strip()
    return out


def matches_labels(obj: dict, sel: dict[str, str]) -> bool:
    labels = obj.get("metadata", {}).get("labels", {})
    return all(labels.get(k) == v for k, v in sel.items())


_WATCH_WINDOW = 2048  # retained events; older watch rvs get Gone (410)

#: Metadata keys the server maintains an equality index over (merged
#: labels-over-annotations, the same precedence every gang-membership
#: reader uses).  ``list_by_meta`` answers these in O(result) instead of
#: the O(store) client-side filtered LIST that made ``_gang_members``
#: ~580k ``is_member`` calls per standard sim trace (ROADMAP bottleneck).
#: ``tpu.dev/priority`` joins the vocabulary with tputopo.priority: a
#: tier-filtered pending lookup ("every serving-tier pod") is O(tier),
#: not O(store) — the informer mirror shares this tuple via MetaIndex,
#: so the authoritative and mirrored indexes can never drift.
INDEXED_META = ("tpu.dev/gang-id", "tpu.dev/priority")


def meta_value(obj: dict, key: str) -> str | None:
    """``key``'s value in an object's merged metadata — labels override
    annotations, matching ``_gang_of``'s ``{**annotations, **labels}``.
    Values are canonicalized per key (:func:`canon_meta_value`), so the
    named and integer spellings of one priority tier share a bucket."""
    md = obj.get("metadata", {})
    labels = md.get("labels") or {}
    if key in labels:
        return canon_meta_value(key, labels[key])
    return canon_meta_value(key, (md.get("annotations") or {}).get(key))


def canon_meta_value(key: str, value: str | None) -> str | None:
    """Canonical index spelling of a metadata value.  The priority key
    accepts aliases ("serving" == "100" — tputopo.k8s.objects), so the
    index buckets — and every :meth:`list_by_meta` lookup — normalize
    through ``parse_priority``; a malformed priority indexes nowhere
    (the lenient read path treats it as unlabeled batch, and unlabeled
    pods are not bucketed either).  Other keys pass through."""
    if value is None or key != "tpu.dev/priority":
        return value
    from tputopo.k8s.objects import parse_priority

    try:
        return str(parse_priority(value))
    except ValueError:
        return None


class MetaIndex:
    """The ``(kind, meta_key, value) -> {store_key: obj}`` equality index
    over :data:`INDEXED_META`, shared by the fake API server and the
    informer mirror so the key vocabulary and the merged-metadata
    precedence rule (:func:`meta_value`) can never drift between the
    authoritative store and the mirror.  Values are the caller's stored
    dicts (no copies); locking is the caller's job."""

    __slots__ = ("_buckets",)

    def __init__(self) -> None:
        self._buckets: dict[tuple[str, str, str],
                            dict[tuple[str, str], dict]] = {}

    def install(self, kind: str, key: tuple[str, str], obj: dict,
                old: dict | None = None) -> None:
        if old is not None:
            self.remove(kind, key, old)
        for mk in INDEXED_META:
            v = meta_value(obj, mk)
            if v is not None:
                self._buckets.setdefault((kind, mk, v), {})[key] = obj

    def remove(self, kind: str, key: tuple[str, str], obj: dict) -> None:
        for mk in INDEXED_META:
            v = meta_value(obj, mk)
            if v is not None:
                bucket = self._buckets.get((kind, mk, v))
                if bucket is not None:
                    bucket.pop(key, None)
                    if not bucket:
                        del self._buckets[(kind, mk, v)]

    def lookup(self, kind: str, key: str, value: str) -> list[dict]:
        """Stored dicts with ``key == value`` (value canonicalized, so a
        lookup by "serving" and one by "100" answer identically);
        unindexed keys raise KeyError so a silent full miss can never
        masquerade as an empty gang."""
        if key not in INDEXED_META:
            raise KeyError(f"meta key {key!r} is not indexed "
                           f"(indexed: {INDEXED_META})")
        return list(self._buckets.get(
            (kind, key, canon_meta_value(key, value)), {}).values())

    def drop_kind(self, kind: str) -> None:
        self._buckets = {mkey: bucket
                         for mkey, bucket in self._buckets.items()
                         if mkey[0] != kind}


_deepcopy = copy.deepcopy


def _digest(obj: dict) -> str:
    """Content digest for the nocopy mutation guard (order-insensitive)."""
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=repr).encode()).hexdigest()


class ObjectHandle:
    """A stable, copy-free reference to one stored object.

    Keyed by (kind, namespace, name), never by dict identity: the handle
    survives annotation patches (in-place mutation on the legacy write
    path, wholesale replacement of the stored incarnation under
    ``nocopy_writes``) AND delete/recreate cycles (a fresh dict under
    the same key — e.g. a requeued sim job's recreated pods).  :meth:`fetch` is the
    handle-based variant of :meth:`FakeApiServer.get_nocopy` and carries
    the same contract: single-threaded readers only, NEVER mutate the
    result.  The sim engine holds one per gang member so its confirm /
    reset-path reads stop paying a deepcopy per pod per event."""

    __slots__ = ("_api", "kind", "name", "namespace")

    def __init__(self, api: "FakeApiServer", kind: str, name: str,
                 namespace: str | None = None) -> None:
        self._api = api
        self.kind = kind
        self.name = name
        self.namespace = namespace

    def fetch(self) -> dict:
        """The current stored object (no copy); raises NotFound when the
        object does not exist right now."""
        return self._api.get_nocopy(self.kind, self.name, self.namespace)

    def __repr__(self) -> str:  # observability only
        return (f"ObjectHandle({self.kind}, "
                f"{self.namespace or ''}/{self.name})")


class FakeApiServer:
    def __init__(self, *, nocopy_writes: bool = False) -> None:
        self._lock = threading.RLock()
        # Copy-free write path (leg 3 of the fleet hot-path pass), OFF by
        # default: when enabled, the mutating verbs (create/create_many
        # staging, patch_annotations/patch_labels, bind_pod) build the
        # new stored object by STRUCTURAL SHARING — a fresh top-level
        # dict with a fresh metadata (and, where mutated, annotations/
        # labels/spec/status) dict, every untouched sub-dict shared with
        # the previous incarnation — and return the stored object itself
        # instead of a deepcopy.  The aliasing contract flips from
        # "patches mutate stored dicts in place" to the STRONGER "no
        # stored dict is ever mutated once handed out": a nocopy reader's
        # reference becomes a frozen snapshot of that resourceVersion.
        # In exchange, write callers inherit the nocopy read contract
        # (NEVER mutate a returned object or the staged input's shared
        # sub-dicts) — the single-threaded sim engine qualifies and
        # enables it; the threaded extender stack keeps the default,
        # whose echoes remain caller-owned deep copies.  The lint nocopy
        # rules and the runtime digest guard police the contract.
        self.nocopy_writes = nocopy_writes
        # guarded-by: _lock|_watch_cond
        self._objects: dict[str, dict[tuple[str, str], dict]] = {
            "nodes": {},
            "pods": {},
        }
        # Store keys per kind, maintained in sorted order (insort on
        # create, bisect removal on delete): every LIST verb returns
        # (namespace, name) order, and re-sorting the whole store per
        # list_nocopy call was ~1.6 s cumulative on the standard sim
        # trace (ROADMAP fleet-scale bottleneck 3).  The store key IS the
        # sort key, so iteration order here matches the old sorted().
        # guarded-by: _lock|_watch_cond
        self._sorted_keys: dict[str, list[tuple[str, str]]] = {
            "nodes": [],
            "pods": [],
        }
        self._rv = 0  # guarded-by: _lock|_watch_cond
        self.events: list[dict] = []  # guarded-by: _lock|_watch_cond
        # Watch machinery: a bounded per-server event log + a condition the
        # watchers block on.  Event = {"type": ADDED|MODIFIED|DELETED,
        # "kind": ..., "rv": int, "object": deepcopy-at-emit}.
        #
        # The deepcopy-at-emit is LAZY: until the first watch consumer
        # attaches (a watch() or list_with_version() call), _emit logs
        # nothing — it only advances the unlogged floor.  A server with no
        # watchers (the sim drives thousands of mutations per trace and
        # never watches) pays zero emit copies; a watcher asking for a
        # resourceVersion older than the floor gets Gone and relists,
        # exactly as if the window had scrolled past it.
        self._watch_log: list[dict] = []  # guarded-by: _lock|_watch_cond
        self._watch_cond = threading.Condition(self._lock)
        self._watch_attached = False  # guarded-by: _lock|_watch_cond
        # rv of the newest UNLOGGED event
        self._watch_floor = 0  # guarded-by: _lock|_watch_cond
        # Nocopy mutation guard (debug mode, off by default): when enabled,
        # every nocopy read records (resourceVersion, content digest); a
        # later read or server write that finds the content changed at an
        # UNCHANGED resourceVersion can only mean a nocopy caller broke the
        # read-only contract — the server's own writes always bump the rv.
        self.nocopy_guard = False
        # guarded-by: _lock|_watch_cond
        self._nocopy_digests: dict[tuple[str, str, str], tuple[str, str]] = {}
        # Meta equality index (shared MetaIndex structure with the
        # informer mirror).  Values are the STORED dicts (same objects as
        # the store), so in-place annotation patches stay visible;
        # maintained on every create/delete and on the two metadata patch
        # verbs — and refreshed on every structural-sharing replacement
        # (nocopy_writes), where the stored dict identity changes per
        # write.
        self._meta_index = MetaIndex()  # guarded-by: _lock|_watch_cond
        # Assignment-key index: pod store keys currently carrying the
        # chip-group assignment annotation (ko.ANN_GROUP).  The GC
        # sweep's candidate universe is exactly these pods, so
        # :meth:`list_assignments` answers in O(assignments) instead of
        # the O(store) listing that made the per-TTL-period expiry scan a
        # profiled fleet hot path.  Maintained at the same points as the
        # meta index.
        self._assign_keys: set[tuple[str, str]] = set()  # guarded-by: _lock|_watch_cond

    # ---- meta equality index ----------------------------------------------

    def _index_obj(self, kind: str, key: tuple[str, str], obj: dict) -> None:  # holds-lock: _lock
        self._meta_index.install(kind, key, obj)
        if kind == "pods" and ANN_GROUP in (
                obj["metadata"].get("annotations") or {}):
            self._assign_keys.add(key)

    def _unindex_obj(self, kind: str, key: tuple[str, str], obj: dict) -> None:  # holds-lock: _lock
        self._meta_index.remove(kind, key, obj)
        if kind == "pods":
            self._assign_keys.discard(key)

    def list_assignments(self) -> list[dict]:
        """The pods currently carrying the chip-group assignment
        annotation, as stored dicts in (namespace, name) order — the
        indexed candidate listing behind the GC's expiry sweep (same
        single-threaded read-only contract as :meth:`list_nocopy`).
        O(assignments), not O(store): Pending arrivals never enter the
        index, so a deep queue costs the sweep nothing."""
        with self._lock:
            store = self._objects["pods"]
            out = [store[k] for k in sorted(self._assign_keys)]
            if self.nocopy_guard:
                for o in out:
                    self._guard_check("pods", o)
                    self._guard_record("pods", o)
        return out

    def list_by_meta(self, kind: str, key: str, value: str,
                     copy: bool = True) -> list[dict]:
        """Objects whose merged metadata maps ``key`` to ``value`` — an
        O(result) index lookup for keys in :data:`INDEXED_META` (others
        raise KeyError so a silent full miss can never masquerade as an
        empty gang).  ``copy=False`` returns the stored dicts under the
        same single-threaded read-only contract as :meth:`list_nocopy`;
        the default deepcopies each hit (still O(result), not O(store)).
        Sorted by (namespace, name) exactly like :meth:`list`."""
        with self._lock:
            objs = self._meta_index.lookup(kind, key, value)
            if self.nocopy_guard and not copy:
                for o in objs:
                    self._guard_check(kind, o)
                    self._guard_record(kind, o)
            if copy:
                objs = [_deepcopy(o) for o in objs]
        return sorted(objs, key=lambda o: (o["metadata"].get("namespace", ""),
                                           o["metadata"]["name"]))

    # ---- nocopy mutation guard --------------------------------------------

    def _guard_key(self, kind: str, obj: dict) -> tuple[str, str, str]:
        md = obj["metadata"]
        return (kind, md.get("namespace") or "", md["name"])

    def _guard_check(self, kind: str, obj: dict) -> None:  # holds-lock: _lock
        """Verify a stored object against its recorded nocopy digest.
        Called before every server-side mutation and on every nocopy read
        (guard mode only) — the moment an illegal caller mutation becomes
        detectable."""
        rec = self._nocopy_digests.get(self._guard_key(kind, obj))
        if rec is None:
            return
        rv = obj["metadata"].get("resourceVersion")
        if rec[0] == rv and rec[1] != _digest(obj):
            raise RuntimeError(
                f"nocopy contract violation: {kind} "
                f"{obj['metadata'].get('namespace')}/{obj['metadata']['name']}"
                f" changed content at unmoved resourceVersion {rv} — a "
                "get_nocopy/list_nocopy caller mutated a stored object")

    def _guard_record(self, kind: str, obj: dict) -> None:  # holds-lock: _lock
        self._nocopy_digests[self._guard_key(kind, obj)] = (
            obj["metadata"].get("resourceVersion"), _digest(obj))

    def verify_nocopy_digests(self) -> None:
        """Check every object a nocopy reader has seen (guard mode): any
        content drift at an unmoved resourceVersion raises.  Tests call
        this after driving a whole flow through the nocopy read paths."""
        with self._lock:
            for (kind, ns, name), _ in list(self._nocopy_digests.items()):
                obj = self._store(kind).get((ns, name))
                if obj is not None:
                    self._guard_check(kind, obj)

    # ---- helpers ----------------------------------------------------------

    def _bump(self, obj: dict) -> None:  # holds-lock: _lock
        self._rv += 1
        obj["metadata"]["resourceVersion"] = str(self._rv)

    def _emit(self, type_: str, kind: str, obj: dict) -> None:  # holds-lock: _lock
        if not self._watch_attached:
            # No watcher has ever attached: nobody can be blocked on the
            # condition, and the event can never be replayed (floor rule in
            # watch()) — skip the log append AND its deepcopy (~10% of sim
            # wall at fleet scale).
            self._watch_floor = self._rv
            return
        self._watch_log.append({"type": type_, "kind": kind, "rv": self._rv,
                                "object": copy.deepcopy(obj)})
        del self._watch_log[:-_WATCH_WINDOW]
        self._watch_cond.notify_all()

    def _attach_watch(self) -> None:
        """First watch consumer: deepcopy-at-emit logging starts now.
        Anything older than the floor is unreconstructable (it was never
        logged) — watch() answers Gone for it, the standard relist path."""
        with self._lock:
            self._watch_attached = True

    def _store(self, kind: str) -> dict[tuple[str, str], dict]:  # holds-lock: _lock
        return self._objects[kind]

    def _sorted_objects(self, kind: str) -> list[dict]:  # holds-lock: _lock
        """Stored dicts in (namespace, name) order — the maintained
        sorted-key list makes this a gather, not a sort."""
        store = self._objects[kind]
        return [store[k] for k in self._sorted_keys[kind]]

    def _key_added(self, kind: str, k: tuple[str, str]) -> None:  # holds-lock: _lock
        insort(self._sorted_keys[kind], k)

    def _key_removed(self, kind: str, k: tuple[str, str]) -> None:  # holds-lock: _lock
        keys = self._sorted_keys[kind]
        i = bisect_left(keys, k)
        if i < len(keys) and keys[i] == k:
            del keys[i]

    # ---- CRUD -------------------------------------------------------------

    @staticmethod
    def _reincarnate(obj: dict) -> dict:
        """THE structural-sharing incarnation every copy-free write
        builds on: a fresh top-level + metadata dict (so the rv bump
        never touches the source object — a caller's input at create, a
        handed-out previous incarnation on the patch/bind/delete verbs),
        everything else — spec, status, the annotation/label dicts
        themselves — shared structurally.  Callers copy exactly the
        sub-dicts they are about to mutate and nothing more; valid
        because under ``nocopy_writes`` no incarnation is ever mutated
        once handed out (every later write replaces wholesale), and
        write callers promise the same for their inputs."""
        return {**obj, "metadata": dict(obj["metadata"])}

    def create(self, kind: str, obj: dict, *, echo: bool = True) -> dict:
        """Store a copy of ``obj`` (callers keep ownership of their
        input) and return the created object.

        ``echo=True`` (default, the K8s REST shape) returns an independent
        deep copy the caller may mutate freely — historically a SECOND full
        deepcopy per create on top of the store copy.  Callers that only
        need the identity/version of what they just created pass
        ``echo=False`` and get a metadata-only stub ({name, namespace,
        resourceVersion}) built without copying the object at all.

        Under ``nocopy_writes`` the store copy is the structural-sharing
        :meth:`_reincarnate` and the echo is the stored object itself —
        the nocopy read contract (never mutate) extends to it."""
        with self._lock:
            md = obj["metadata"]
            k = _key(md.get("namespace"), md["name"])
            store = self._store(kind)
            if k in store:
                raise Conflict(f"{kind} {k} already exists")
            copy_ = self._reincarnate(obj) if self.nocopy_writes \
                else copy.deepcopy(obj)
            self._bump(copy_)
            store[k] = copy_
            self._key_added(kind, k)
            self._index_obj(kind, k, copy_)
            self._emit("ADDED", kind, copy_)
            if echo:
                return copy_ if self.nocopy_writes else copy.deepcopy(copy_)
            return {"metadata": {
                "name": md["name"],
                "namespace": md.get("namespace"),
                "resourceVersion": copy_["metadata"]["resourceVersion"],
            }}

    def create_many(self, kind: str, objs: Iterable[dict]) -> int:
        """Bulk staging: create ``objs`` under ONE lock acquisition and
        without the per-call deepcopy of each return value — what the sim
        (tputopo.sim) uses to stage hundreds of nodes/pods per trace,
        where create()'s echo copies dominated setup.  Watch semantics
        are identical: one ADDED event per object, in input order."""
        objs = list(objs)
        with self._lock:
            store = self._store(kind)
            # Validate the WHOLE batch before storing anything: a mid-batch
            # Conflict must not leave the server half-staged with partial
            # ADDED events already emitted (all-or-nothing, unlike a loop
            # of create() calls).
            keys = [_key(o["metadata"].get("namespace"), o["metadata"]["name"])
                    for o in objs]
            if len(set(keys)) != len(keys):
                raise Conflict(f"duplicate names within {kind} batch")
            for k in keys:
                if k in store:
                    raise Conflict(f"{kind} {k} already exists")
            for obj, k in zip(objs, keys):
                copy_ = self._reincarnate(obj) if self.nocopy_writes \
                    else copy.deepcopy(obj)
                self._bump(copy_)
                store[k] = copy_
                self._key_added(kind, k)
                self._index_obj(kind, k, copy_)
                self._emit("ADDED", kind, copy_)
        return len(objs)

    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        with self._lock:
            try:
                return copy.deepcopy(self._store(kind)[_key(namespace, name)])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None

    def get_nocopy(self, kind: str, name: str,
                   namespace: str | None = None) -> dict:
        """Get WITHOUT deepcopying the stored object.

        Same contract as :meth:`list_nocopy`: strictly for single-threaded
        read-only consumers (the sim engine's confirm path and policy
        place() re-fetched every member pod per event, and the deepcopy
        chain behind :meth:`get` was ~30% of sim wall).  Callers MUST NOT
        mutate the returned dict; concurrent writers make the view racy —
        on the legacy write path annotation patches mutate stored dicts
        in place, while under ``nocopy_writes`` a held reference stays
        frozen at its resourceVersion and silently goes stale instead.
        The threaded extender stack keeps using :meth:`get`."""
        with self._lock:
            try:
                obj = self._store(kind)[_key(namespace, name)]
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None
            if self.nocopy_guard:
                self._guard_check(kind, obj)
                self._guard_record(kind, obj)
            return obj

    def handle(self, kind: str, name: str,
               namespace: str | None = None) -> ObjectHandle:
        """A key-stable :class:`ObjectHandle` for repeated nocopy reads of
        one object (the handle-based ``get_nocopy`` variant).  The object
        need not exist yet — :meth:`ObjectHandle.fetch` resolves the key
        at read time."""
        return ObjectHandle(self, kind, name, namespace)

    def list(self, kind: str, selector: Callable[[dict], bool] | None = None,
             label_selector: dict[str, str] | None = None) -> list[dict]:
        with self._lock:
            out = [copy.deepcopy(o) for o in self._sorted_objects(kind)]
        if label_selector:
            out = [o for o in out if matches_labels(o, label_selector)]
        if selector:
            out = [o for o in out if selector(o)]
        return out  # already in (namespace, name) order

    def list_nocopy(self, kind: str,
                    selector: Callable[[dict], bool] | None = None) -> list[dict]:
        """List WITHOUT deepcopying the stored objects.

        Strictly for single-threaded read-only consumers — the sim
        (tputopo.sim) drives thousands of ClusterState syncs per trace,
        and the deepcopy in :meth:`list` was ~80% of its wall clock.
        Callers MUST NOT mutate the returned dicts, and concurrent
        writers make the view racy (in-place patches on the legacy
        write path; frozen-but-stale snapshots under ``nocopy_writes``
        — see :meth:`get_nocopy`); the threaded extender stack keeps
        using :meth:`list`."""
        with self._lock:
            out = self._sorted_objects(kind)
            if self.nocopy_guard:
                for o in out:
                    self._guard_check(kind, o)
                    self._guard_record(kind, o)
        if selector:
            out = [o for o in out if selector(o)]
        return out  # already in (namespace, name) order

    def list_with_version(self, kind: str) -> tuple[list[dict], str]:
        """(items, list resourceVersion) — the informer's initial sync point:
        a watch from this rv sees exactly the mutations after this list.
        Attaches the watch log (lazy-emit opt-out ends here): every event
        after the returned rv is guaranteed logged, so the follow-up watch
        never gets a spurious Gone for the list-to-watch gap."""
        with self._lock:
            self._watch_attached = True
            out = [copy.deepcopy(o) for o in self._sorted_objects(kind)]
            rv = str(self._rv)
        return out, rv

    def watch(self, kind: str, resource_version: str,
              timeout_s: float = 30.0):
        """Yield events for ``kind`` with rv > resource_version, blocking up
        to ``timeout_s`` for new ones; returns on timeout (the caller
        re-watches from its last seen rv, exactly the K8s watch contract).
        Raises Gone when resource_version predates the retained window —
        or predates the lazy-emit floor (events before the first watch
        consumer attached were never logged; the caller relists, the same
        recovery as a scrolled window)."""
        try:
            last = int(resource_version)
        except (TypeError, ValueError):
            raise ValueError(f"bad resourceVersion {resource_version!r}") from None
        self._attach_watch()
        deadline = time.monotonic() + timeout_s
        while True:
            with self._watch_cond:
                if last < self._watch_floor:
                    raise Gone(f"resourceVersion {last} too old (events "
                               f"through {self._watch_floor} predate the "
                               "first watch attach)")
                if self._watch_log and last < self._watch_log[0]["rv"] - 1:
                    raise Gone(f"resourceVersion {last} too old "
                               f"(window starts at {self._watch_log[0]['rv']})")
                pending = [e for e in self._watch_log
                           if e["rv"] > last and e["kind"] == kind]
                if not pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        current = self._rv
                        break  # emit a closing BOOKMARK outside the lock
                    self._watch_cond.wait(remaining)
                    continue
            for e in pending:
                last = e["rv"]
                yield {"type": e["type"], "object": copy.deepcopy(e["object"]),
                       "rv": str(e["rv"])}
        # Closing BOOKMARK: advances an idle kind's watcher to the current
        # global rv so churn on the *other* kind can't push its position
        # out of the retained window (spurious Gone -> relist otherwise).
        if current > last:
            yield {"type": "BOOKMARK",
                   "object": {"metadata": {"resourceVersion": str(current)}},
                   "rv": str(current)}

    def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        with self._lock:
            try:
                obj = self._store(kind).pop(_key(namespace, name))
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None
            self._key_removed(kind, _key(namespace, name))
            self._unindex_obj(kind, _key(namespace, name), obj)
            if self.nocopy_guard:
                self._guard_check(kind, obj)
                self._nocopy_digests.pop(self._guard_key(kind, obj), None)
            # _bump (not a bare rv increment): the event's object must carry
            # the delete's own resourceVersion — the REST watch leg derives
            # its progress from object metadata, and a stale rv there makes
            # the stream replay the trailing delete forever.  Under
            # nocopy_writes the bump lands on a structurally-shared event
            # incarnation: the popped object itself must stay frozen for
            # any nocopy reader still holding it.
            if self.nocopy_writes:
                obj = self._reincarnate(obj)
            self._bump(obj)
            self._emit("DELETED", kind, obj)

    # ---- metadata patches (the handshake's transport) ----------------------

    def patch_annotations(self, kind: str, name: str, patch: dict[str, str | None],
                          namespace: str | None = None,
                          expect_version: str | None = None) -> dict:
        """Merge ``patch`` into metadata.annotations (None deletes a key).

        ``expect_version`` enables compare-and-swap: the optimistic token the
        two-phase ASSUME/ASSIGNED handshake relies on (design.md:227-246).
        """
        with self._lock:
            try:
                obj = self._store(kind)[_key(namespace, name)]
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None
            if self.nocopy_guard:
                self._guard_check(kind, obj)
            if expect_version is not None and \
                    obj["metadata"].get("resourceVersion") != expect_version:
                raise Conflict(
                    f"{kind} {name}: resourceVersion {expect_version} is stale"
                )
            store_key = _key(namespace, name)
            self._unindex_obj(kind, store_key, obj)
            if self.nocopy_writes:
                # Structural sharing: a NEW incarnation (_reincarnate)
                # replaces the stored object wholesale; the previous one
                # — and any nocopy reference to it — stays frozen at its
                # resourceVersion.  Only the annotation dict is copied,
                # never the whole pod.
                new_obj = self._reincarnate(obj)
                new_md = new_obj["metadata"]
                anns = dict(new_md.get("annotations") or {})
                new_md["annotations"] = anns
            else:
                new_obj = obj
                anns = obj["metadata"].setdefault("annotations", {})
            for k, v in patch.items():
                if v is None:
                    anns.pop(k, None)
                else:
                    anns[k] = str(v)
            if new_obj is not obj:
                self._store(kind)[store_key] = new_obj
            self._index_obj(kind, store_key, new_obj)
            self._bump(new_obj)
            self._emit("MODIFIED", kind, new_obj)
            self.events.append({"type": "patch", "kind": kind, "name": name,
                                "patch": dict(patch)})
            return new_obj if self.nocopy_writes else copy.deepcopy(new_obj)

    def patch_labels(self, kind: str, name: str, patch: dict[str, str | None],
                     namespace: str | None = None) -> dict:
        """Merge ``patch`` into metadata.labels (None deletes a key)."""
        with self._lock:
            try:
                obj = self._store(kind)[_key(namespace, name)]
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None
            if self.nocopy_guard:
                self._guard_check(kind, obj)
            store_key = _key(namespace, name)
            self._unindex_obj(kind, store_key, obj)
            if self.nocopy_writes:
                new_obj = self._reincarnate(obj)
                new_md = new_obj["metadata"]
                labels = dict(new_md.get("labels") or {})
                new_md["labels"] = labels
            else:
                new_obj = obj
                labels = obj["metadata"].setdefault("labels", {})
            for k, v in patch.items():
                if v is None:
                    labels.pop(k, None)
                else:
                    labels[k] = str(v)
            if new_obj is not obj:
                self._store(kind)[store_key] = new_obj
            self._index_obj(kind, store_key, new_obj)
            self._bump(new_obj)
            self._emit("MODIFIED", kind, new_obj)
            return new_obj if self.nocopy_writes else copy.deepcopy(new_obj)

    # ---- binding (the extender's bind verb target) -------------------------

    def bind_pod(self, name: str, node_name: str, namespace: str | None = None) -> dict:
        with self._lock:
            try:
                pod = self._store("pods")[_key(namespace, name)]
            except KeyError:
                raise NotFound(f"pod {namespace}/{name}") from None
            if self.nocopy_guard:
                self._guard_check("pods", pod)
            if pod["spec"].get("nodeName"):
                raise Conflict(f"pod {name} already bound to {pod['spec']['nodeName']}")
            if self.nocopy_writes:
                key = _key(namespace, name)
                new_pod = self._reincarnate(pod)
                new_pod["spec"] = dict(pod["spec"])
                new_pod["spec"]["nodeName"] = node_name
                new_pod["status"] = dict(pod.get("status") or {})
                new_pod["status"]["phase"] = "Scheduled"
                # Replacement changes the stored dict identity — the meta
                # index values are the stored dicts, so reinstall.
                self._unindex_obj("pods", key, pod)
                self._store("pods")[key] = new_pod
                self._index_obj("pods", key, new_pod)
            else:
                pod["spec"]["nodeName"] = node_name
                pod["status"]["phase"] = "Scheduled"
                new_pod = pod
            self._bump(new_pod)
            self._emit("MODIFIED", "pods", new_pod)
            self.events.append({"type": "bind", "name": name, "node": node_name})
            return new_pod if self.nocopy_writes else copy.deepcopy(new_pod)

    # ---- convenience for tests --------------------------------------------

    def pods_on_node(self, node_name: str) -> list[dict]:
        return self.list("pods", lambda p: p["spec"].get("nodeName") == node_name)

    def add_nodes(self, nodes: Iterable[dict]) -> None:
        for n in nodes:
            self.create("nodes", n, echo=False)  # nobody reads the echo
