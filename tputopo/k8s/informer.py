"""List+watch informer cache — the extender's cheap cluster view.

The reference declares ``nodeCacheCapable: true`` (design.md:102): the
extender is expected to maintain its own view of cluster state rather than
re-LIST the world per scheduling verb.  Round 1 re-synced with two
cluster-wide LISTs per ``sort`` (VERDICT r1 #6 — O(cluster) per verb at
real pod counts); this informer replaces that with the standard Kubernetes
client pattern: one initial LIST per kind (recording the list
resourceVersion), then a WATCH from that version applying ADDED / MODIFIED
/ DELETED events to a local store.  A watch failure or 410 Gone triggers a
relist; metrics count lists / events / relists so "zero LISTs in steady
state" is provable.

The informer exposes the read half of the FakeApiServer surface
(``list``/``get``), so :class:`~tputopo.extender.state.ClusterState` can
sync *from the cache* unchanged.  It also keeps a bounded journal of
content-changing events (:meth:`Informer.events_since`) so a derived-state
holder can fold the delta between two version tokens instead of rebuilding
— the watch-delta maintenance path.  Writes keep going to the real API — the
cache is eventually consistent, which is safe where it is used: ``sort``
scores from the cache; ``bind`` plans from the cache too but its writes go
through the API server's optimistic concurrency and are written through to
the mirror immediately (``observe``), so the extender's own placements are
never stale in its own view (ExtenderConfig docstring).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable

from tputopo.k8s.fakeapi import (Gone, MetaIndex, NotFound, matches_labels)


def _obj_rv(obj: dict) -> int:
    """Numeric resourceVersion for newest-wins comparisons (0 if absent —
    real API servers guarantee monotonically increasing integers)."""
    try:
        return int(obj.get("metadata", {}).get("resourceVersion", 0))
    except (TypeError, ValueError):
        return 0


def _key(obj: dict) -> tuple[str, str]:
    md = obj["metadata"]
    return (md.get("namespace") or "", md["name"])


class Informer:
    """Maintains a local mirror of ``kinds`` via list+watch threads."""

    def __init__(self, api, kinds: tuple[str, ...] = ("nodes", "pods"),
                 watch_timeout_s: float = 30.0,
                 relist_backoff_s: float = 1.0) -> None:
        self.api = api
        self.kinds = kinds
        self.watch_timeout_s = watch_timeout_s
        self.relist_backoff_s = relist_backoff_s
        # guarded-by: _lock
        self._store: dict[str, dict[tuple[str, str], dict]] = {
            k: {} for k in kinds}
        # Mirror-side meta equality index — the same MetaIndex structure
        # (and key vocabulary / precedence rule) as the fake API server's
        # authoritative one.  Maintained wherever a mirror entry is
        # installed/removed (_relist / _apply / observe), so gang-member
        # lookup against the mirror is O(gang) instead of a filtered LIST
        # of every pod — and, with tpu.dev/priority in INDEXED_META
        # (tputopo.priority), a tier-filtered pending lookup is O(tier).
        self._meta_index = MetaIndex()  # guarded-by: _lock
        self._rv: dict[str, str] = {}  # guarded-by: _lock
        # Content version: bumped ONLY when the mirror's content actually
        # changes (install of a new/newer object, a delete that removed
        # something, a relist).  The watch position (_rv) advances on every
        # event, but an event that is the echo of a write-through observe()
        # re-delivers an object the mirror already holds at the same
        # resourceVersion — content identical, so derived state (the
        # extender's ClusterState) stays coherent and must not be
        # invalidated.  This is what lets bind apply its own delta instead
        # of paying an O(pods) re-sync per call (VERDICT r3 #1).
        self._content = 0  # guarded-by: _lock
        # Delta journal: one entry per content bump EXCEPT relists (which
        # bump content without an entry — the resulting gap is exactly what
        # tells events_since() that only a full rebuild is exact).  Entry =
        # (content_after, kind, event_type, stored_object).  Bounded: a
        # consumer whose token fell off the window falls back to a full
        # sync, same as after a relist.
        # guarded-by: _lock
        self._journal: deque[tuple[int, str, str, dict]] = deque(maxlen=256)
        self._lock = threading.Lock()
        self._synced = {k: threading.Event() for k in kinds}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # Counter increments are dict-slot read-modify-writes and the
        # per-kind watch threads share this dict — each ``+= 1`` holds
        # the mirror lock (the lockset rule flagged the former bare
        # increments as lost-update races).  The key SET is fixed here,
        # so lock-free scrape-side iteration (server /metrics) stays
        # safe; writes are what serialize.
        # guarded-by: _lock
        self.metrics = {"lists": 0, "watch_events": 0, "relists": 0,
                        "watch_errors": 0, "observes": 0,
                        "unordered_deletes_kept": 0}
        self._observe_count = 0

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> "Informer":
        for kind in self.kinds:
            t = threading.Thread(target=self._run, args=(kind,),
                                 name=f"informer-{kind}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.watch_timeout_s + 5)

    def wait_synced(self, timeout: float = 30.0) -> bool:
        return all(ev.wait(timeout) for ev in self._synced.values())

    @property
    def synced(self) -> bool:
        return all(ev.is_set() for ev in self._synced.values())

    @property
    def journal_len(self) -> int:
        """Current depth of the bounded delta journal — a /metrics gauge:
        pinned at the maxlen under sustained churn, it predicts
        journal-gap fallbacks (consumers whose token fell off the window
        pay a full rebuild)."""
        with self._lock:
            return len(self._journal)

    def version(self) -> tuple[str, ...]:
        """Cache-coherence token: changes iff the mirror's CONTENT changed
        (install of a new/newer object, a removing delete, a relist, a
        write-through observe).  The echo watch event of an object the
        mirror already holds at the same resourceVersion does NOT move the
        token — derived state stays reusable across a verb's own write
        coming back through the watch.  Lets consumers reuse derived state
        (e.g. the extender's ClusterState) until content actually moves."""
        with self._lock:
            return (str(self._content),)

    def observe(self, kind: str, obj: dict) -> tuple[str, ...]:
        """Assume-cache write-through (the kube-scheduler cache pattern):
        the caller just wrote ``obj`` successfully (its own PATCH/bind) and
        must not wait a watch round-trip to see its own write — the next
        ``sort`` would otherwise plan against pre-bind state and hand out
        already-assigned chips.  Upsert is keyed, so the eventual watch
        event is idempotent; a *stale* concurrent event cannot regress the
        mirror because older resourceVersions lose.

        Returns the post-install version token (atomically, under the
        mirror lock): a caller whose pre-write token was exactly one step
        older knows its own write is the ONLY content change in between
        and may delta-apply it to derived state instead of re-syncing."""

        with self._lock:
            if kind in self._store:
                key = _key(obj)
                cur = self._store[kind].get(key)
                obj_rv, cur_rv = _obj_rv(obj), _obj_rv(cur or {})
                # Same escape hatch as _apply: two rv-less objects are
                # unordered — install (can't prove identity) and bump.
                if cur is None or obj_rv > cur_rv or obj_rv == cur_rv == 0:
                    self._store[kind][key] = obj
                    self._index_install(kind, key, cur, obj)
                    self._content += 1
                    self._journal.append((self._content, kind, "MODIFIED", obj))
                    self._observe_count += 1
                    self.metrics["observes"] += 1
            return (str(self._content),)

    def events_since(self, version: tuple[str, ...]
                     ) -> tuple[list[tuple[str, str, dict]], tuple[str, ...]] | None:
        """The content-changing events between ``version`` (a token a
        consumer previously got from :meth:`version`/:meth:`observe`) and
        now, as ``([(kind, event_type, object), ...], new_token)`` — what a
        derived-state holder folds in instead of rebuilding.  Returns None
        when the span is not exactly reconstructible (a relist landed, the
        token fell off the bounded journal, or the token is unparseable):
        the consumer must fall back to a full rebuild.  Returned objects
        are the mirror's stored dicts — read-only by the same contract as
        ``list(copy=False)``."""
        try:
            since = int(version[0])
        except (TypeError, ValueError, IndexError):
            return None
        with self._lock:
            cur = self._content
            token = (str(cur),)
            if since == cur:
                return [], token
            if since > cur:
                return None  # token from a different informer incarnation
            tail = [e for e in self._journal if e[0] > since]
            # Exactly one journal entry per content bump in the span, or
            # the span includes a relist/evicted entry — not reconstructible.
            if len(tail) != cur - since:
                return None
            return [(kind, etype, obj) for _, kind, etype, obj in tail], token

    # ---- meta index maintenance (call under self._lock) --------------------

    def _index_install(self, kind: str, key: tuple[str, str],  # holds-lock: _lock
                       old: dict | None, new: dict) -> None:
        self._meta_index.install(kind, key, new, old=old)

    def _index_remove(self, kind: str, key: tuple[str, str],  # holds-lock: _lock
                      obj: dict) -> None:
        self._meta_index.remove(kind, key, obj)

    def _index_rebuild(self, kind: str) -> None:  # holds-lock: _lock
        self._meta_index.drop_kind(kind)
        for key, obj in self._store[kind].items():
            self._meta_index.install(kind, key, obj)

    # ---- list+watch loop ---------------------------------------------------

    def _relist(self, kind: str) -> None:
        items, rv = self.api.list_with_version(kind)
        try:
            snap_rv = int(rv)
        except (TypeError, ValueError):
            snap_rv = 0
        with self._lock:
            new_store = {_key(o): o for o in items}
            # Newest-wins merge: the snapshot was taken at snap_rv OUTSIDE
            # the lock, so a concurrent bind's write-through observe() may
            # have installed strictly newer objects — a wholesale swap
            # would regress the mirror to pre-bind state and re-offer
            # just-assigned chips until the re-watch catches up.
            for key, cur in self._store[kind].items():
                cur_rv = _obj_rv(cur)
                if cur_rv > snap_rv and cur_rv > _obj_rv(new_store.get(key, {})):
                    new_store[key] = cur
            self._store[kind] = new_store
            self._index_rebuild(kind)
            self._rv[kind] = rv
            self._content += 1  # conservative: a relist may change anything
            self.metrics["lists"] += 1
        self._synced[kind].set()

    def _apply(self, kind: str, event: dict) -> None:
        obj = event["object"]
        with self._lock:
            if event["type"] == "BOOKMARK":
                pass  # rv checkpoint only; the object is not a real one
            elif event["type"] == "DELETED":
                # A lagging DELETE for an OLDER incarnation must not remove
                # a newer object installed by observe() (delete-then-
                # recreate under watch lag); keep when the mirror's version
                # is strictly newer.  An rv-less DELETE (rv 0 — real API
                # servers always set one; this hardens the fake-API path)
                # is unordered: it also must not remove a known-newer
                # object, so it only wins against an rv-less mirror entry.
                key = _key(obj)
                cur = self._store[kind].get(key)
                del_rv = _obj_rv(obj)
                if cur is not None and _obj_rv(cur) > del_rv:
                    if del_rv == 0:
                        self.metrics["unordered_deletes_kept"] += 1
                else:
                    removed = self._store[kind].pop(key, None)
                    if removed is not None:
                        self._index_remove(kind, key, removed)
                        self._content += 1
                        self._journal.append(
                            (self._content, kind, "DELETED", obj))
            else:  # ADDED / MODIFIED — upsert, newest resourceVersion wins
                # (an event older than a write-through observe() of the
                # same object must not regress the mirror).  An event at
                # the SAME resourceVersion as the mirror entry is the echo
                # of an observe(): identical content, skip entirely so the
                # version token doesn't move.  Two rv-less objects are
                # unordered — install (can't prove identity) and bump.
                key = _key(obj)
                cur = self._store[kind].get(key)
                obj_rv, cur_rv = _obj_rv(obj), _obj_rv(cur or {})
                if cur is None or obj_rv > cur_rv or obj_rv == cur_rv == 0:
                    self._store[kind][key] = obj
                    self._index_install(kind, key, cur, obj)
                    self._content += 1
                    self._journal.append(
                        (self._content, kind, event["type"], obj))
            if event.get("rv"):
                self._rv[kind] = event["rv"]
            self.metrics["watch_events"] += 1

    def _run(self, kind: str) -> None:
        while not self._stop.is_set():
            try:
                if not self._synced[kind].is_set():
                    self._relist(kind)
                # Lint-driven fix: _rv is written by _apply/_relist under
                # the mirror lock; snapshot the watch position under it
                # too instead of the former bare cross-thread dict read.
                with self._lock:
                    watch_from = self._rv[kind]
                for event in self.api.watch(
                        kind, watch_from,
                        timeout_s=self.watch_timeout_s):
                    self._apply(kind, event)
                    if self._stop.is_set():
                        return
                # Timed out quietly: re-watch from the last seen rv.
            except Gone:
                with self._lock:
                    self.metrics["relists"] += 1
                self._synced[kind].clear()
            # tpulint: disable=except-contract -- deliberate thread-main-loop boundary: any transport exception class (REST client hangups included) must degrade to backoff+relist, counted as watch_errors, never kill the watch thread
            except Exception:
                if self._stop.is_set():
                    return
                # Transport hiccup: back off, then resync from scratch —
                # the store may have missed events.
                with self._lock:
                    self.metrics["watch_errors"] += 1
                self._synced[kind].clear()
                self._stop.wait(self.relist_backoff_s)

    # ---- read surface (FakeApiServer-compatible) ---------------------------

    def list(self, kind: str, selector: Callable[[dict], bool] | None = None,
             label_selector: dict[str, str] | None = None,
             copy: bool = True) -> list[dict]:
        """Mirror snapshot.  ``copy=False`` returns the stored objects
        themselves — for read-only consumers on the hot path (the
        extender's per-sort ClusterState rebuild measures ~5 ms of pure
        deepcopy on a 16-node cluster otherwise); such callers MUST NOT
        mutate the returned dicts.  See :meth:`get_nocopy` for the full
        aliasing contract the no-mutation rule rests on."""
        import copy as copymod
        with self._lock:
            objs = list(self._store[kind].values())
        out = [copymod.deepcopy(o) for o in objs] if copy else objs
        if label_selector:
            out = [o for o in out if matches_labels(o, label_selector)]
        if selector:
            out = [o for o in out if selector(o)]
        return sorted(out, key=lambda o: (o["metadata"].get("namespace", ""),
                                          o["metadata"]["name"]))

    def list_by_meta(self, kind: str, key: str, value: str,
                     copy: bool = True) -> list[dict]:
        """Mirror objects whose merged metadata maps ``key`` to ``value``
        — the informer half of :meth:`FakeApiServer.list_by_meta`
        (O(result) via the maintained index; unindexed keys raise
        KeyError).  ``copy=False`` returns the mirrored dicts under the
        same read-only contract as ``list(copy=False)`` — the
        :meth:`get_nocopy` aliasing contract.  Sorted by
        (namespace, name)."""
        import copy as copymod
        with self._lock:
            objs = self._meta_index.lookup(kind, key, value)
        if copy:
            objs = [copymod.deepcopy(o) for o in objs]
        return sorted(objs, key=lambda o: (o["metadata"].get("namespace", ""),
                                           o["metadata"]["name"]))

    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        import copy
        with self._lock:
            try:
                return copy.deepcopy(
                    self._store[kind][(namespace or "", name)])
            except KeyError:
                pass
        raise NotFound(f"{kind} {namespace}/{name} (informer cache)")

    def get_nocopy(self, kind: str, name: str,
                   namespace: str | None = None) -> dict:
        """Get WITHOUT deepcopying the mirrored object — the same
        single-threaded/read-only contract as ``list(copy=False)`` and
        :meth:`FakeApiServer.get_nocopy`.

        The aliasing contract, stated precisely (it is what every
        ``copy=False`` read here relies on): the returned dict is a
        consistent snapshot of the object at its resourceVersion because
        NOBODY mutates an installed incarnation in place — the mirror
        only ever replaces entries wholesale (``_apply``/``observe``/
        ``_relist``), and every source feeding it hands over objects
        that are frozen from the moment they arrive.  Watch events are
        deepcopied at emit and REST watch objects are freshly decoded,
        so those entries are mirror-owned; a write-through ``observe``
        may instead install an object that ALIASES the API server's
        stored incarnation (the fake server's bind/patch return).  Under
        the server's structural-sharing write path (``nocopy_writes``)
        that alias is still a frozen snapshot — the server builds a NEW
        incarnation per write and never touches a handed-out one — so
        the guarantee holds by the same no-in-place-mutation discipline
        on both sides.  Under the legacy deepcopy write path the
        observe() input is a caller-owned deep copy, so the entry is
        mirror-owned there too.  Either way: callers MUST NOT mutate
        the result, and the threaded extender verbs keep using
        :meth:`get`."""
        with self._lock:
            try:
                return self._store[kind][(namespace or "", name)]
            except KeyError:
                pass
        raise NotFound(f"{kind} {namespace}/{name} (informer cache)")
