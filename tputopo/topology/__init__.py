"""Topology core: TPU generation specs, ICI torus model, cost model, slice
enumeration, and all-reduce bandwidth scoring.

This package is the TPU-native replacement for the reference's topology
stack: the ``gpuTopology`` pairwise matrix (design.md:61-74), the link
taxonomy and affinity marks (design.md:31-47, 194-203), the device-combination
selector (design.md:131-190), the combo scorer (design.md:205-217), and the
Gaia access-cost tree (Gaia PDF §III.B).  A TPU pod is a regular 2D/3D torus
with known coordinates, so pairwise discovery is replaced by an analytic
model and subset search by contiguous sub-slice enumeration.
"""

from tputopo.topology.generations import (  # noqa: F401
    TpuGeneration,
    GENERATIONS,
    get_generation,
)
from tputopo.topology.model import ChipTopology, parse_topology  # noqa: F401
from tputopo.topology.cost import LinkType, LinkCostModel, classify_link  # noqa: F401
from tputopo.topology.slices import (  # noqa: F401
    SliceShape,
    Placement,
    enumerate_shapes,
    enumerate_placements,
    Allocator,
)
from tputopo.topology.score import predict_allreduce_gbps, score_chip_set  # noqa: F401
from tputopo.topology.baselines import (  # noqa: F401
    BASELINE_PICKERS,
    get_picker,
    naive_pick,
    register_picker,
)
