"""Health->scheduler loop (VERDICT r1 #2): a chip the device plugin marks
Unhealthy must leave the schedulable pool immediately (node annotation ->
cluster state -> selector), and assignments stranded on dead silicon must
be surfaced with their gang."""

import pytest

from tests.cluster import build_cluster
from tputopo.extender import ClusterState, ExtenderConfig, ExtenderScheduler
from tputopo.extender.scheduler import (BindError, LABEL_GANG_ID,
                                        LABEL_GANG_SIZE)
from tputopo.k8s import make_pod
from tputopo.k8s import objects as ko


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_dead_chip_leaves_the_schedulable_pool():
    clock = Clock()
    api, plugins = build_cluster(clock=clock)
    sched = ExtenderScheduler(api, ExtenderConfig(), clock=clock)
    plugins["node-0"].set_health("0,0,0", healthy=False)
    # Annotation published:
    anns = api.get("nodes", "node-0")["metadata"]["annotations"]
    assert anns[ko.ANN_UNHEALTHY] == "0,0,0"
    # State excludes it:
    state = ClusterState(api, clock=clock).sync()
    assert (0, 0, 0) in state.domains["slice-a"].unhealthy
    assert (0, 0, 0) not in state.free_chips_on_node("node-0")
    # A full-host request on node-0 is now infeasible; other nodes fine.
    api.create("pods", make_pod("p4", chips=4))
    scores = {s["Host"]: s["Score"]
              for s in sched.sort(api.get("pods", "p4", "default"),
                                  [f"node-{i}" for i in range(4)])}
    assert scores["node-0"] == 0
    assert all(scores[f"node-{i}"] > 0 for i in (1, 2, 3))
    with pytest.raises(BindError):
        sched.bind("p4", "default", "node-0")
    # Placements elsewhere never touch the dead chip.
    decision = sched.bind("p4", "default", "node-1")
    assert [0, 0, 0] not in decision["chips"]


def test_health_restore_clears_annotation_and_pool():
    clock = Clock()
    api, plugins = build_cluster(clock=clock)
    plugins["node-0"].set_health("0,0,0", healthy=False)
    plugins["node-0"].set_health("0,0,0", healthy=True)
    anns = api.get("nodes", "node-0")["metadata"]["annotations"]
    assert ko.ANN_UNHEALTHY not in anns
    state = ClusterState(api, clock=clock).sync()
    assert not state.domains["slice-a"].unhealthy
    assert (0, 0, 0) in state.free_chips_on_node("node-0")


def test_gang_on_dead_chip_is_surfaced():
    clock = Clock()
    api, plugins = build_cluster(clock=clock)
    sched = ExtenderScheduler(api, ExtenderConfig(), clock=clock)
    for i in range(2):
        api.create("pods", make_pod(f"dp-{i}", chips=4, labels={
            LABEL_GANG_ID: "job-x", LABEL_GANG_SIZE: "2"}))
    nodes = [f"node-{i}" for i in range(4)]
    bound = []
    for i in range(2):
        pod = api.get("pods", f"dp-{i}", "default")
        best = max(sched.sort(pod, nodes), key=lambda s: s["Score"])
        bound.append(sched.bind(f"dp-{i}", "default", best["Host"]))
    # Kill one chip of member 0's placement.
    victim_node = bound[0]["node"]
    victim_chip = ",".join(str(x) for x in bound[0]["chips"][0])
    plugins[victim_node].set_health(victim_chip, healthy=False)
    state = ClusterState(api, clock=clock).sync()
    dom = state.domains["slice-a"]
    assert [pa.gang_id for pa in dom.on_unhealthy] == ["job-x"]
    report = state.fragmentation_report()["slice-a"]
    assert report["assignments_on_unhealthy"] == [
        {"pod": f"default/{bound[0]['pod'].split('/')[1]}", "gang": "job-x"}]
    assert report["unhealthy_chips"] == [bound[0]["chips"][0]]
    # The dead chip stays accounted (not free) and new placements avoid it.
    assert tuple(bound[0]["chips"][0]) not in dom.allocator.free


def test_bogus_unhealthy_annotation_does_not_wedge_sync():
    clock = Clock()
    api, _ = build_cluster(clock=clock)
    api.patch_annotations("nodes", "node-0", {ko.ANN_UNHEALTHY: "9,9,9"})
    state = ClusterState(api, clock=clock).sync()  # must not raise
    assert not state.domains["slice-a"].unhealthy
