"""Multi-host bootstrap: env-contract resolution (fast) and a REAL
two-process CPU rendezvous through jax.distributed (slow tier)."""

import os
import socket
import subprocess
import sys

import pytest

from tputopo.workloads.distributed import (ProcessGroup,
                                           process_group_from_env)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_default_is_single_process():
    g = process_group_from_env({})
    assert g == ProcessGroup(coordinator=None, num_processes=1, process_id=0)
    assert g.single


def test_indexed_job_contract():
    g = process_group_from_env({
        "TPUTOPO_NUM_PROCESSES": "4",
        "TPUTOPO_COORDINATOR": "llama-dp4-0.llama-dp4",
        "JOB_COMPLETION_INDEX": "2",
    })
    assert g.num_processes == 4
    assert g.process_id == 2
    # Bare host gets the framework's default port.
    assert g.coordinator == "llama-dp4-0.llama-dp4:8476"


def test_explicit_process_id_wins_over_job_index():
    g = process_group_from_env({
        "TPUTOPO_NUM_PROCESSES": "2",
        "TPUTOPO_COORDINATOR": "c:1234",
        "TPUTOPO_PROCESS_ID": "1",
        "JOB_COMPLETION_INDEX": "0",
    })
    assert g.process_id == 1
    assert g.coordinator == "c:1234"


def test_worker_id_fallback():
    g = process_group_from_env({
        "TPUTOPO_NUM_PROCESSES": "2",
        "TPUTOPO_COORDINATOR": "c",
        "TPU_WORKER_ID": "1",
    })
    assert g.process_id == 1


def test_cloud_tpu_task_id_fallback():
    g = process_group_from_env({
        "TPUTOPO_NUM_PROCESSES": "2",
        "TPUTOPO_COORDINATOR": "c",
        "CLOUD_TPU_TASK_ID": "1",
    })
    assert g.process_id == 1


def test_single_process_ignores_worker_ordinal():
    """The device plugin injects TPU_WORKER_ID into EVERY container; a
    1-pod job on a non-zero host is still rank 0 of 1, not a crash."""
    g = process_group_from_env({"TPU_WORKER_ID": "3",
                                "JOB_COMPLETION_INDEX": "2"})
    assert g == ProcessGroup(coordinator=None, num_processes=1, process_id=0)


def test_multi_process_without_coordinator_is_loud():
    with pytest.raises(ValueError, match="TPUTOPO_COORDINATOR"):
        process_group_from_env({"TPUTOPO_NUM_PROCESSES": "2"})


def test_rank_out_of_range_is_loud():
    with pytest.raises(ValueError, match="out of range"):
        process_group_from_env({
            "TPUTOPO_NUM_PROCESSES": "2",
            "TPUTOPO_COORDINATOR": "c:1",
            "TPUTOPO_PROCESS_ID": "2",
        })


_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 1)
from tputopo.workloads.distributed import initialize_from_env
g = initialize_from_env(initialization_timeout=120)
assert jax.process_count() == g.num_processes, jax.process_count()
assert jax.device_count() == g.num_processes, jax.device_count()
from jax.experimental import multihost_utils
import jax.numpy as jnp
val = multihost_utils.broadcast_one_to_all(jnp.asarray(g.process_id + 41))
print("RESULT", g.process_id, int(val), jax.device_count())
"""


from jax_features import requires_num_cpu_devices  # noqa: E402


# The _WORKER subprocess relies on the jax_num_cpu_devices config
# option; without it the rendezvous leg cannot run on this JAX.
@requires_num_cpu_devices
def test_two_process_cpu_rendezvous():
    """Two actual processes rendezvous through jax.distributed on CPU:
    process/device counts span both, and a broadcast from rank 0 reaches
    rank 1 — the real multi-host code path at toy scale."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "TPUTOPO_NUM_PROCESSES": "2",
            "TPUTOPO_COORDINATOR": f"127.0.0.1:{port}",
            "TPUTOPO_PROCESS_ID": str(rank),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env, cwd=REPO))
    outs = []
    for rank, proc in enumerate(procs):
        try:
            stdout, stderr = proc.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise AssertionError(f"rank {rank} hung in rendezvous")
        assert proc.returncode == 0, f"rank {rank}: {stderr[-2000:]}"
        outs.append(stdout)
    for rank, out in enumerate(outs):
        # rank 0 broadcast 41; every rank must see it over 2 global devices.
        assert f"RESULT {rank} 41 2" in out, outs
